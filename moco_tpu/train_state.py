"""The MoCo training state pytree (SURVEY §5.4 build spec).

Everything the reference keeps as module/optimizer state —
`encoder_q`/`encoder_k` parameters, BN running stats for both encoders, the
SGD momentum buffers, the negative queue + pointer (`state_dict` buffers in
the reference, `main_moco.py:≈L322-328`) — lives in ONE explicit, replicated
pytree. The train step is `state' = f(state, batch)` with the state donated,
so XLA updates params/queue in place in HBM. Checkpointing this pytree with
Orbax is bit-faithful resume (queue and pointer included), matching the
reference's torch.save of the full state_dict.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax

from moco_tpu.ops.queue import init_queue


@flax.struct.dataclass
class TrainState:
    step: jax.Array                 # int32 scalar, number of completed steps
    params_q: Any                   # query encoder params (trainable)
    params_k: Any                   # key encoder params (EMA of params_q)
    batch_stats_q: Any              # query-encoder BN running stats
    batch_stats_k: Any              # key-encoder BN running stats
    opt_state: Any                  # optax state over params_q only
    queue: jax.Array | None         # [K, dim] negative keys (None for v3)
    queue_ptr: jax.Array | None     # int32 ring pointer (None for v3)
    rng: jax.Array                  # replicated base PRNG key (model-side RNG)
    # gradient-sync accumulators (ISSUE 6; parallel/gradsync.py): `{}` for
    # the stateless modes (fused/bucketed — dialect-1-compatible on disk),
    # else {"acc": <params-shaped tree>} of PER-DEVICE leaves with a leading
    # [n_dev] axis sharded over the data mesh — the quantized mode's
    # error-feedback residual / the demo mode's local momentum. Carried in
    # the state so checkpoints resume compression exactly (dialect 2,
    # checkpoint.TRAIN_STATE_DIALECTS; ties the checkpoint to the mesh size
    # — restore falls back to fresh zeros on mismatch).
    gradsync: Any = dataclasses.field(default_factory=dict)


def create_train_state(
    rng: jax.Array,
    model,
    tx: optax.GradientTransformation,
    input_shape: tuple[int, ...],
    num_negatives: int | None,
    embed_dim: int,
    queue_dtype=jnp.float32,
) -> TrainState:
    """Initialise q, copy q → k (the reference's param copy,
    `moco/builder.py:≈L20-24` — k starts identical to q), build queue.

    `input_shape` is a per-device-shaped dummy `[local_b, H, W, C]`; init is
    shape-driven only.
    """
    init_key, queue_key, state_key = jax.random.split(rng, 3)
    variables = model.init(init_key, jnp.zeros(input_shape, jnp.float32), train=False)
    params_q = variables["params"]
    batch_stats_q = variables.get("batch_stats", {})
    params_k = jax.tree.map(jnp.copy, params_q)
    batch_stats_k = jax.tree.map(jnp.copy, batch_stats_q)
    if num_negatives is not None:
        queue, queue_ptr = init_queue(queue_key, num_negatives, embed_dim, queue_dtype)
    else:
        queue, queue_ptr = None, None
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params_q=params_q,
        params_k=params_k,
        batch_stats_q=batch_stats_q,
        batch_stats_k=batch_stats_k,
        opt_state=tx.init(params_q),
        queue=queue,
        queue_ptr=queue_ptr,
        rng=state_key,
    )
