"""ZeRO-1-style optimizer-state sharding over the data mesh (opt-in).

The reference replicates everything per GPU (SURVEY §2.11: "full replica per
GPU"); this is the TPU-idiomatic upgrade that costs one sharding annotation:
optimizer-state leaves (SGD/LARS momentum, AdamW mu/nu — one to two extra
f32 copies of every parameter) are sharded over the `data` axis instead of
replicated, cutting their HBM footprint by the mesh size. The scaling-book
recipe verbatim — pick the mesh, annotate the sharding, let the pjit
partitioner insert the collectives:

- the momentum update runs SHARDED (elementwise on each device's slice of
  the state, with the replicated gradient sliced for free);
- `optax.apply_updates` needs replicated updates, so the partitioner inserts
  one all-gather per step — riding ICI, overlapped with the update phase;
- numerics are equivalent to float-reduction tolerance (the same elementwise
  math on the same values; only XLA's fusion order shifts at the partition
  boundary, ~1e-7 relative) — pinned by tests/test_zero.py.

Parameters/BN stats/queue stay replicated: MoCo's encoders fit per-chip
(SURVEY §2.11 keeps TP out of scope), and the queue must be replicated for
the identical-enqueue invariant. Any optimizer leaf WITH a mesh-divisible
axis shards (including mesh-divisible 1-D bias/BN momenta); only leaves
with no such axis (scalars, step counts, odd-sized vectors) stay
replicated.

Enable with `--zero-sharding true`; `jax.jit` propagates the committed input
shardings, so no step-function changes are needed.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from moco_tpu.parallel.mesh import DATA_AXIS


def opt_state_shardings(opt_state, mesh):
    """Sharding pytree for an optax state: each array leaf sharded over the
    data axis on its LARGEST mesh-divisible axis, else replicated."""
    replicated = NamedSharding(mesh, P())

    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        best = None
        for ax, s in enumerate(shape):
            if s > 0 and s % mesh.size == 0:
                if best is None or s > shape[best]:
                    best = ax
        if best is None:
            return replicated
        parts = [None] * len(shape)
        parts[best] = DATA_AXIS
        return NamedSharding(mesh, P(*parts))

    return jax.tree.map(spec, opt_state)


def shard_opt_state(opt_state, mesh):
    """Place an (unsharded or replicated) optax state per the ZeRO layout."""
    return jax.tree.map(
        lambda leaf, s: jax.device_put(leaf, s),
        opt_state,
        opt_state_shardings(opt_state, mesh),
    )


def pdevice_state_shardings(tree, mesh):
    """Shardings for PER-DEVICE state carried in the replicated TrainState
    (ISSUE 6: gradsync error-feedback / local-momentum accumulators): every
    leaf has a leading device axis of size `mesh.size`, split over the data
    axis so each device holds exactly its own `[1, ...]` slice — the same
    footprint-per-chip argument as the ZeRO layout above, except here the
    split axis is semantic (slice i IS device i's state), not just a
    partitioning choice. On the 2-D data×fsdp mesh (ISSUE 15) the leading
    axis splits over BOTH axes — n_dev is still the total device count and
    slice i is still device i's state, in the mesh's row-major order."""
    replicated = NamedSharding(mesh, P())
    sharded = NamedSharding(
        mesh, P(tuple(str(a) for a in mesh.axis_names)))

    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        return sharded if shape and shape[0] == mesh.size else replicated

    return jax.tree.map(spec, tree)


def shard_pdevice_state(tree, mesh):
    """Place per-device-state leaves on their owning devices (see
    `pdevice_state_shardings`); applied at creation and re-applied after a
    resume, which restores the leaves replicated."""
    return jax.tree.map(
        lambda leaf, s: jax.device_put(leaf, s),
        tree,
        pdevice_state_shardings(tree, mesh),
    )
