"""Device mesh + process topology (layer L0/L1 of SURVEY.md §1).

The reference scales with one POSIX process per GPU launched by `mp.spawn`
and a NCCL process group (`main_moco.py:≈L114-155`). TPU-native equivalent:
a single controller process per *host* drives all local chips, the SPMD
program is compiled once over a `jax.sharding.Mesh`, and multi-host
bootstrap is `jax.distributed.initialize()` (replacing the tcp:// / env://
rendezvous of `torch.distributed.init_process_group`). Collectives are
compiled into the step program over ICI/DCN — there is no user-visible
process-group object.

MoCo's only parallelism is data parallelism (SURVEY.md §2.11), so the mesh
is 1-D over `DATA_AXIS`. TP/PP/EP are structurally absent from the reference
and deliberately not built (SURVEY.md §7 non-goals); pjit makes them
available later by re-sharding if ever needed.
"""

from __future__ import annotations

import os
import re
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The primary mesh axis. On the 1-D data-parallel mesh (the seed layout)
# the batch dim is sharded over it and params/queue/opt-state are
# replicated. ISSUE 15 adds a second, FSDP axis: on the 2-D mesh the batch
# shards over BOTH axes (data parallelism spans every device) while
# params/optimizer state shard over the fsdp axis only — the fast
# intra-pod axis on real hardware, so the per-step param all-gathers ride
# ICI while the (optionally quantized) inter-pod grad hop rides DCN.
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"

# PretrainConfig.sharding values (mirrored as literals in config.py, which
# must stay importable without jax)
SHARDING_MODES = ("dp", "fsdp", "fsdp_tp")


def force_cpu_devices(n: int = 8) -> None:
    """Force this process onto `n` fake CPU devices (test/simulation mode).

    Replaces the reference's "just run it on 8 V100s" validation story
    (SURVEY.md §4): `--xla_force_host_platform_device_count=N` gives N real
    XLA CPU devices in one process with real all_gather/psum/ppermute
    semantics. Must run before the first JAX backend query.

    Note: the environment's sitecustomize force-registers a TPU ("axon")
    platform and overrides `JAX_PLATFORMS`, so setting the env var alone is
    not enough — we also set the config in-process.

    An explicit `n` REPLACES any count already present in XLA_FLAGS: an
    elastic resize relaunch (ISSUE 11) passes the NEW count via
    `--fake-devices` while the child env still carries the old
    incarnation's flags — respecting the stale value would silently pin
    every relaunch to the original mesh and make the resize a no-op.
    (Still before the first backend query, as ever: once the CPU client
    exists the count is baked.)
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={n}", flags,
        )
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def distributed_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bootstrap (replaces `dist.init_process_group`, SURVEY §5.8).

    On Cloud TPU all three args are auto-detected from the metadata server
    (pass nothing); explicit args support manual rendezvous. Callers invoke
    this only for multi-host jobs (the train driver's `--multihost` path) —
    `num_processes=1` is the explicit single-process no-op.
    """
    if num_processes == 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def create_mesh(num_devices: int | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the 1-D data-parallel mesh over all (or the first N) devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} present"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def create_mesh_2d(
    fsdp_size: int,
    num_devices: int | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """The 2-D (data, fsdp) mesh (ISSUE 15). `fsdp_size` devices form each
    param-shard group (the INNER, fast axis); the outer data axis carries
    plain replica parallelism across groups. Device order is preserved
    from the flat list, so a (1, N) mesh reduces over exactly the same
    device sequence as the 1-D mesh — the bitwise-parity anchor the fsdp
    tests pin."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} present"
            )
        devices = devices[:num_devices]
    n = len(devices)
    if fsdp_size < 1 or n % fsdp_size != 0:
        raise ValueError(
            f"fsdp axis size {fsdp_size} must divide the device count {n}"
        )
    return Mesh(
        np.asarray(devices).reshape(n // fsdp_size, fsdp_size),
        (DATA_AXIS, FSDP_AXIS),
    )


def default_fsdp_size(sharding: str, n_devices: int) -> int:
    """The fsdp-axis size a `sharding_axis_size=0` config resolves to:
    all devices for pure fsdp; for fsdp_tp the largest proper divisor
    (e.g. 4 devices → data 2 × fsdp 2, 8 → 2×4) — a placeholder for the
    real intra-pod group size, which `sharding_axis_size` pins on
    hardware whose topology is known."""
    if sharding == "fsdp":
        return n_devices
    for d in range(n_devices // 2, 0, -1):
        if n_devices % d == 0:
            return d
    return 1


def mesh_for_config(config, mesh: Mesh | None = None,
                    num_devices: int | None = None) -> Mesh:
    """The mesh `config.sharding` needs, rebuilt from `mesh`'s own devices
    when the provided one has the wrong axis set (the driver and tests
    hand in the plain 1-D mesh; fsdp runs fold it into the 2-D layout
    without changing the device order)."""
    mode = getattr(config, "sharding", "dp")
    devices = None
    if mesh is not None:
        devices = list(mesh.devices.flat)
    if mode == "dp":
        if mesh is not None and tuple(mesh.axis_names) == (DATA_AXIS,):
            return mesh
        return create_mesh(num_devices, devices=devices)
    n = len(devices) if devices is not None else len(
        jax.devices()[:num_devices] if num_devices else jax.devices())
    fsdp_size = int(getattr(config, "sharding_axis_size", 0)) or \
        default_fsdp_size(mode, n)
    if mode == "fsdp" and fsdp_size != n:
        raise ValueError(
            f"sharding='fsdp' shards over ALL {n} devices; "
            f"sharding_axis_size={fsdp_size} asks for a sub-group — that "
            "is the fsdp_tp hybrid, say so explicitly"
        )
    if (mesh is not None
            and tuple(mesh.axis_names) == (DATA_AXIS, FSDP_AXIS)
            and mesh.shape[FSDP_AXIS] == fsdp_size):
        return mesh
    return create_mesh_2d(fsdp_size, num_devices, devices=devices)


def batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """The mesh axes the global batch shards over — ALL of them: on the
    2-D mesh data parallelism spans every device, the fsdp axis only
    changes where params live."""
    return tuple(str(a) for a in mesh.axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for replicated state (params, queue, opt state)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch: leading dim split over every mesh axis (the
    1-D data axis, or data×fsdp on the 2-D mesh — same global batch
    semantics either way)."""
    return NamedSharding(mesh, P(batch_axes(mesh)))


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-device batch (the reference's `batch_size / ngpus_per_node`,
    `main_moco.py:≈L230`). Global batch must divide evenly: the queue ring
    update requires `K % global_batch == 0` and XLA requires even sharding."""
    n = mesh.size
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by mesh size {n}")
    return global_batch // n
