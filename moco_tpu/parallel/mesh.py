"""Device mesh + process topology (layer L0/L1 of SURVEY.md §1).

The reference scales with one POSIX process per GPU launched by `mp.spawn`
and a NCCL process group (`main_moco.py:≈L114-155`). TPU-native equivalent:
a single controller process per *host* drives all local chips, the SPMD
program is compiled once over a `jax.sharding.Mesh`, and multi-host
bootstrap is `jax.distributed.initialize()` (replacing the tcp:// / env://
rendezvous of `torch.distributed.init_process_group`). Collectives are
compiled into the step program over ICI/DCN — there is no user-visible
process-group object.

MoCo's only parallelism is data parallelism (SURVEY.md §2.11), so the mesh
is 1-D over `DATA_AXIS`. TP/PP/EP are structurally absent from the reference
and deliberately not built (SURVEY.md §7 non-goals); pjit makes them
available later by re-sharding if ever needed.
"""

from __future__ import annotations

import os
import re
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The single mesh axis used by the whole framework. Batch dim is sharded over
# it; params/queue/opt-state are replicated over it.
DATA_AXIS = "data"


def force_cpu_devices(n: int = 8) -> None:
    """Force this process onto `n` fake CPU devices (test/simulation mode).

    Replaces the reference's "just run it on 8 V100s" validation story
    (SURVEY.md §4): `--xla_force_host_platform_device_count=N` gives N real
    XLA CPU devices in one process with real all_gather/psum/ppermute
    semantics. Must run before the first JAX backend query.

    Note: the environment's sitecustomize force-registers a TPU ("axon")
    platform and overrides `JAX_PLATFORMS`, so setting the env var alone is
    not enough — we also set the config in-process.

    An explicit `n` REPLACES any count already present in XLA_FLAGS: an
    elastic resize relaunch (ISSUE 11) passes the NEW count via
    `--fake-devices` while the child env still carries the old
    incarnation's flags — respecting the stale value would silently pin
    every relaunch to the original mesh and make the resize a no-op.
    (Still before the first backend query, as ever: once the CPU client
    exists the count is baked.)
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={n}", flags,
        )
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def distributed_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bootstrap (replaces `dist.init_process_group`, SURVEY §5.8).

    On Cloud TPU all three args are auto-detected from the metadata server
    (pass nothing); explicit args support manual rendezvous. Callers invoke
    this only for multi-host jobs (the train driver's `--multihost` path) —
    `num_processes=1` is the explicit single-process no-op.
    """
    if num_processes == 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def create_mesh(num_devices: int | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the 1-D data-parallel mesh over all (or the first N) devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} present"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for replicated state (params, queue, opt state)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-device batch (the reference's `batch_size / ngpus_per_node`,
    `main_moco.py:≈L230`). Global batch must divide evenly: the queue ring
    update requires `K % global_batch == 0` and XLA requires even sharding."""
    n = mesh.size
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by mesh size {n}")
    return global_batch // n
