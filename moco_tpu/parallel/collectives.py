"""ShuffleBN and gather collectives (TPU-native rebuild of `moco/builder.py`'s
`concat_all_gather` / `_batch_shuffle_ddp` / `_batch_unshuffle_ddp`, SURVEY §2.2).

All functions here are called INSIDE a `jax.shard_map`-mapped step over the
1-D data mesh, so `lax.all_gather(..., axis_name)` compiles to a single XLA
all-gather over ICI. Differences from the NCCL reference, by design:

- The reference generates the shuffle permutation on rank 0 and broadcasts it
  (`moco/builder.py:≈L72-98`, one NCCL broadcast per step). Here every device
  computes the SAME permutation from a shared, replicated PRNG key
  (`jax.random.permutation(key, B)`): deterministic ⇒ consistent ⇒ the
  broadcast disappears entirely (zero comm).
- `concat_all_gather` in the reference is explicitly non-differentiable (it
  is only used under `no_grad`). `lax.all_gather` IS differentiable, so
  callers that need the reference's stop-grad semantics wrap results in
  `lax.stop_gradient` (the train step does this for the key path).

Replication-typing note (jax 0.9): `lax.all_gather` output is typed
"varying" over the mapped axis even though its value is device-invariant
(there is no `all_gather_invariant` in this version). Consequently updates to
REPLICATED state (queue, params) that derive from gathered values must happen
at the outer jit level, outside the shard_map region — the train step is a
hybrid: `jit(outer)` does EMA/optimizer/queue updates under the automatic
partitioner, and the inner `shard_map` region does only the per-device work
(ShuffleBN, forwards, local grads + psum). This keeps `check_vma` on.

Why ShuffleBN exists (SURVEY §0.1): with per-device BatchNorm, the query and
its positive key would share BN statistics if they sat on the same device,
leaking which in-batch sample is the positive. Shuffling the key batch
across devices before the key encoder's forward decorrelates the BN groups;
unshuffling after restores q/k alignment.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.utils.compat import axis_size, optimization_barrier

# Every collective here accepts either one axis name or a TUPLE of names
# (ISSUE 15: the 2-D data×fsdp mesh) — jax's collectives treat a tuple as
# one combined device group in row-major order of the names given, so the
# helpers below define the matching combined size/index once.


def batch_axis_size(axis_name) -> jax.Array | int:
    """Total device count of the (possibly multi-axis) batch group."""
    if isinstance(axis_name, (tuple, list)):
        n = 1
        for ax in axis_name:
            n = n * axis_size(ax)
        return n
    return axis_size(axis_name)


def batch_axis_index(axis_name) -> jax.Array:
    """This device's rank within the combined batch group, row-major in
    the axis order given — by construction the position its tiled
    `all_gather` shard lands at (pinned by tests/test_collectives.py)."""
    if isinstance(axis_name, (tuple, list)):
        idx = jnp.int32(0)
        for ax in axis_name:
            idx = idx * axis_size(ax) + lax.axis_index(ax)
        return idx
    return lax.axis_index(axis_name)


def all_gather_batch(x: jax.Array, axis_name, chunks: int = 1) -> jax.Array:
    """Gather local batch shards into the global batch along dim 0.

    Equivalent of `concat_all_gather` (`moco/builder.py:≈L167-180`) minus the
    stop-grad (callers add it where the reference ran under no_grad).

    `chunks > 1` is the FAST-style schedule (PAPERS.md): the local batch is
    split into `chunks` row slices, each gathered as its OWN collective,
    chained through `optimization_barrier` so they issue as a deterministic
    pipeline — chunk i can be on the wire while the compute feeding chunk
    i+1 still runs, instead of one monolithic end-of-phase gather. The
    reassembled result is BIT-IDENTICAL to the unchunked gather (rows are
    restitched device-major), so the knob is pure scheduling. A chunk
    count the local batch does not divide falls back to the monolithic
    gather (chunking is a hint, never a shape constraint).
    """
    if chunks <= 1 or x.shape[0] % chunks:
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    rows = x.shape[0] // chunks
    gathered = []
    prev = None
    for c in range(chunks):
        part = lax.slice_in_dim(x, c * rows, (c + 1) * rows, axis=0)
        if prev is not None:
            part, prev = optimization_barrier((part, prev))
        g = lax.all_gather(part, axis_name, axis=0)  # [n, rows, ...]
        gathered.append(g)
        prev = g
    # [C, n, rows, ...] -> [n, C, rows, ...] -> [n * C * rows, ...]:
    # device-major, then original row order within each device's shard —
    # exactly the tiled gather's layout
    stacked = jnp.stack(gathered, axis=0)
    moved = jnp.swapaxes(stacked, 0, 1)
    return moved.reshape((-1,) + tuple(x.shape[1:]))


def batch_shuffle(
    x: jax.Array, key: jax.Array, axis_name, chunks: int = 1
) -> tuple[jax.Array, jax.Array]:
    """Shuffle the global batch across devices; return (local shard, perm).

    Rebuild of `_batch_shuffle_ddp` (`moco/builder.py:≈L72-98`):
      all-gather → same permutation everywhere (shared PRNG key instead of a
      rank-0 broadcast) → each device keeps its contiguous slice.

    `key` MUST be replicated across the mesh (derived by `fold_in` from the
    replicated train-state key) — divergent keys would silently desynchronise
    the shuffle; tests/test_collectives.py pins this.

    `axis_name` may be a tuple (the 2-D mesh — ISSUE 15 generalizes
    ShuffleBN to arbitrary mesh shapes); `chunks` applies the FAST-style
    chunked gather schedule (see `all_gather_batch`).
    """
    n = batch_axis_size(axis_name)
    idx = batch_axis_index(axis_name)
    x_all = all_gather_batch(x, axis_name, chunks)  # [B_global, ...]
    global_b = x_all.shape[0]
    perm = jax.random.permutation(key, global_b)
    local_idx = lax.dynamic_slice_in_dim(perm, idx * (global_b // n), global_b // n)
    return jnp.take(x_all, local_idx, axis=0), perm


def batch_unshuffle(x: jax.Array, perm: jax.Array, axis_name,
                    chunks: int = 1) -> jax.Array:
    """Undo `batch_shuffle` (rebuild of `_batch_unshuffle_ddp`,
    `moco/builder.py:≈L100-115`): gather the shuffled global batch, index it
    with this device's slice of the inverse permutation."""
    n = batch_axis_size(axis_name)
    idx = batch_axis_index(axis_name)
    x_all = all_gather_batch(x, axis_name, chunks)
    global_b = x_all.shape[0]
    inv = jnp.argsort(perm)
    local_idx = lax.dynamic_slice_in_dim(inv, idx * (global_b // n), global_b // n)
    return jnp.take(x_all, local_idx, axis=0)


def chained_psum(flats: list[jax.Array], axis_name: str) -> list[jax.Array]:
    """Per-bucket psums chained through `optimization_barrier` (ISSUE 6).

    Each element of `flats` is one flat gradient bucket. A plain loop of
    psums leaves XLA free to merge them back into one fused end-of-step
    all-reduce — exactly the serialization bucketing exists to break. The
    barrier ties bucket i+1's INPUT to bucket i's OUTPUT, so the reduces
    issue as a deterministic pipeline: bucket i can be on the wire while
    the backward that produces bucket i+1 is still running (DeAR,
    PAPERS.md). On builds whose barrier is identity (utils/compat.py) the
    numerics are unchanged — only the scheduling hint is lost."""
    out = []
    prev = None
    for flat in flats:
        if prev is not None:
            flat, prev = optimization_barrier((flat, prev))
        summed = lax.psum(flat, axis_name)
        out.append(summed)
        prev = summed
    return out


def quantized_psum_mean(
    segments: list[jax.Array], axis_name: str, n: int, wire_dtype: str
) -> tuple[list[jax.Array], list[jax.Array]]:
    """Compress→psum→dequant one bucket of flat f32 segments (one segment
    per gradient leaf); returns `(means, errors)` aligned with `segments`.

    `wire_dtype="int8"`: symmetric int8 with PER-SEGMENT scales, shared
    across devices via ONE `pmax` of the stacked per-segment absmaxes (a
    single tiny vector reduce per bucket). The scale must follow the leaf,
    not the bucket: a multi-MiB bucket spans layers whose gradient
    magnitudes differ by orders of magnitude, and one bucket-wide scale
    would quantize the small-magnitude layers to all-zeros on the wire
    every step — a hidden sync starvation error feedback only undoes one
    quantum at a time. Shared scales keep the dequantized mean
    bit-identical across devices (the DP-safety invariant). The whole
    bucket still rides ONE concatenated psum, on an int32 carrier: summing
    n int8 values overflows int8 for n >= 2, and XLA exposes no
    in-collective requantization (EQuARX does this inside the ring; the
    int8 PAYLOAD plus one f32 scale per leaf is what the byte accounting
    counts).

    `wire_dtype="bfloat16"`: cast→psum→f32, the legacy grad_allreduce path
    — but returning the local cast error so callers can carry error
    feedback, which the legacy path never had.

    `errors` are the LOCAL quantization residuals (input minus what the
    wire carried for this device) — the error-feedback accumulator
    re-injects them into the next step's gradient."""
    if wire_dtype == "int8":
        absmax = lax.pmax(
            jnp.stack([jnp.max(jnp.abs(s)) for s in segments]), axis_name
        )
        scales = jnp.maximum(absmax, jnp.float32(1e-30)) / 127.0
        qs = [
            jnp.clip(jnp.round(s / scales[i]), -127, 127).astype(jnp.int8)
            for i, s in enumerate(segments)
        ]
        flat = jnp.concatenate(qs) if len(qs) > 1 else qs[0]
        summed = lax.psum(flat.astype(jnp.int32), axis_name)
        means, errs, off = [], [], 0
        for i, (s, q) in enumerate(zip(segments, qs)):
            seg = summed[off:off + s.size]
            off += s.size
            means.append(seg.astype(jnp.float32) * scales[i] / n)
            errs.append(s - q.astype(jnp.float32) * scales[i])
        return means, errs
    if wire_dtype == "bfloat16":
        qs = [s.astype(jnp.bfloat16) for s in segments]
        flat = jnp.concatenate(qs) if len(qs) > 1 else qs[0]
        summed = lax.psum(flat, axis_name).astype(jnp.float32)
        means, errs, off = [], [], 0
        for s, q in zip(segments, qs):
            means.append(summed[off:off + s.size] / n)
            off += s.size
            errs.append(s - q.astype(jnp.float32))
        return means, errs
    raise ValueError(f"unknown quantized wire dtype {wire_dtype!r}")


def ring_shuffle(x: jax.Array, axis_name, inverse: bool = False) -> jax.Array:
    """Cheaper ShuffleBN variant: HALF-SHARD ring roll via two `ppermute`s.

    Rotating WHOLE local batches would be a functional no-op for ShuffleBN —
    BN statistics depend only on group MEMBERSHIP, and moving an intact
    group to another device leaves its composition (and thus the q↔k batch
    signature MoCo guards against) unchanged. Instead each device's new
    group is [tail half of shard i-2, head half of shard i-1]: every key-side
    BN group mixes samples from TWO different query-side groups and every
    query group is split across two key groups — partial decorrelation at
    2 half-shard ppermutes instead of a full all-gather. The gather+permute
    `batch_shuffle` stays the semantically faithful default
    (`shuffle_mode="permute"`). A tuple axis runs the ring over the
    combined row-major device group (ISSUE 15 mesh generalization).
    """
    n = batch_axis_size(axis_name)
    if x.shape[0] % 2:
        raise ValueError("ring_shuffle requires an even local batch")
    h = x.shape[0] // 2
    if h == 0 or n == 1:
        return x
    head, tail = x[:h], x[h:]
    if not inverse:
        # shuffled_i = [tail_{i-2}, head_{i-1}]
        recv_tail = lax.ppermute(tail, axis_name, [(i, (i + 2) % n) for i in range(n)])
        recv_head = lax.ppermute(head, axis_name, [(i, (i + 1) % n) for i in range(n)])
        return jnp.concatenate([recv_tail, recv_head], axis=0)
    # inverse: device j's tail sits as part 0 on device j+2, its head as
    # part 1 on device j+1
    back_tail = lax.ppermute(head, axis_name, [(i, (i - 2) % n) for i in range(n)])
    back_head = lax.ppermute(tail, axis_name, [(i, (i - 1) % n) for i in range(n)])
    return jnp.concatenate([back_head, back_tail], axis=0)


def multihop_quantized_psum_mean(
    segments: list[jax.Array],
    inter_axis: str,
    intra_axis: str,
    n_inter: int,
    n_intra: int,
    wire_dtype: str,
) -> tuple[list[jax.Array], list[jax.Array]]:
    """DynamiQ-style topology-aware two-hop reduce (PAPERS.md; ISSUE 15).

    Hop 1 — EXACT f32 psum over `intra_axis` (the fast intra-pod links:
    compression there would spend accuracy where bandwidth is free).
    Hop 2 — compress→psum→dequant over `inter_axis` (the slow inter-pod
    links) through the SAME int8/bf16 machinery as the single-hop
    `quantized_psum_mean`, so the shared-scale / int32-carrier invariants
    carry over unchanged. Returns `(means, errors)` like the single-hop
    reduce.

    Error feedback across hops: quantization acts on the INTRA-SUMMED
    value, which every member of an intra group shares — so the raw
    residual is a per-GROUP quantity. Each device stores residual/n_intra:
    next step every member re-injects its share into its local gradient,
    and hop 1's exact sum reassembles the full residual, exactly once
    (carrying the whole residual on every member would amplify it
    n_intra-fold per step — a hidden positive feedback loop).
    """
    summed_intra = [lax.psum(s, intra_axis) for s in segments]
    means, group_errs = quantized_psum_mean(
        summed_intra, inter_axis, n_inter, wire_dtype
    )
    # the inter hop's mean divided by n_inter only; fold in the intra fan-in
    means = [m / n_intra for m in means]
    errs = [e / n_intra for e in group_errs]
    return means, errs
