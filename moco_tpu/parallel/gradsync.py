"""Communication-efficient gradient synchronization (ISSUE 6 tentpole).

The seed step synced gradients with ONE fused end-of-step `lax.pmean` over
every leaf (`train_step._pmean_grads`): the interconnect idles during
backprop, then the whole reduce serializes on the critical path. This module
replaces it with a selectable strategy behind `PretrainConfig.grad_sync`,
built once per step-build and invoked INSIDE the shard_map region (where the
data axis exists), with a small replicated-merge hook at the outer jit level
for the sparse mode:

  fused      — the seed behavior, kept as the exact-DP default: one tree-wide
               `pmean` (per-leaf dtype policy below). Bitwise identical to
               the pre-ISSUE-6 program.
  bucketed   — DeAR-style (PAPERS.md): grad leaves are packed into
               size-targeted buckets (`grad_sync_bucket_mb`), issued as
               SEPARATE per-bucket psums chained with `optimization_barrier`
               so the reduces issue in a deterministic sequence as their
               buckets' grads become ready — the scheduler can overlap each
               reduce with the rest of the backward instead of fusing
               everything into one end-of-step all-reduce. Numerically the
               same adds in the same element order: bitwise-equal to fused.
  quantized  — EQuARX-style (PAPERS.md): per-bucket compress→psum→dequant in
               int8 (per-LEAF pmax-shared scales so small-magnitude layers
               are not starved by a bucket-wide absmax; the psum rides an
               int32 carrier so partial sums cannot wrap — a native EQuARX
               collective reduces in int8 inside the ring, which XLA does
               not expose, so the int8 payload + one f32 scale per leaf is
               what the byte accounting counts) or bfloat16.
               A persistent PER-DEVICE error-feedback accumulator
               (`TrainState.gradsync["acc"]`) re-injects this step's
               quantization error into next step's gradient, which is what
               makes compressed DP converge (DP-safe: params stay replicated
               because the dequantized mean is identical everywhere).
  demo       — DeMo-style (PAPERS.md) decoupled momentum: each device keeps
               a LOCAL momentum accumulator fed by its LOCAL gradient; only
               the top-k fraction (`grad_sync_topk`) of that slow component
               is synchronized — as (values, indices) pairs whose merge rides
               a small all-gather — and only every `grad_sync_cadence` steps.
               The transmitted component is subtracted from the local
               momentum (the decoupling); the untransmitted residue keeps
               accumulating. Sync bytes drop by orders of magnitude
               (topk/cadence); convergence is gated by a bounded-divergence
               test, not parity.

Per-leaf dtype policy (the `_pmean_grads` "bfloat16" path folded in, with
the mixed-precision interaction made explicit — ISSUE 6 satellite):

  - `None` leaves pass through untouched (they are empty pytree nodes).
  - integer/bool leaves are SUMMED exactly in their native dtype, never
    averaged and never cast: a non-float leaf in a grads-shaped tree is a
    counter, and quantizing or averaging one silently corrupts it.
  - floating leaves reduce on the wire in their OWN dtype under the
    `"float32"` policy (a bf16 leaf is not silently up-cast, which would
    double its wire bytes), and in bfloat16 under the `"bfloat16"` policy —
    cast BACK to the leaf's original dtype afterwards (the old code cast
    everything to f32, which silently widened bf16 leaves).

State layout: the quantized/demo accumulator is per-device, but TrainState
is a replicated outer-level pytree — so each accumulator leaf carries a
leading device axis (`[n_dev, *param_shape]`, sharded over the data axis by
`zero.shard_pdevice_state`) and the shard_map region sees its own `[1, ...]`
slice. This makes the accumulator checkpointable through the ordinary Orbax
path (dialect 2, see checkpoint.TRAIN_STATE_DIALECTS) at the cost of tying
the checkpoint to the mesh size; restore falls back to fresh zeros when the
shapes (or an old dialect) don't match.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.parallel.collectives import (
    chained_psum,
    multihop_quantized_psum_mean,
    quantized_psum_mean,
)
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.utils.compat import optimization_barrier

GRAD_SYNC_MODES = ("fused", "bucketed", "quantized", "demo")
STATE_KEY = "acc"  # the one gradsync accumulator leaf-tree in TrainState


def leaf_wire_dtype(dtype, allreduce_dtype: str):
    """The on-wire reduce dtype for one leaf under the fused/bucketed
    policy. Raises on unknown policy strings (the `_pmean_grads` contract,
    pinned by tests/test_grad_allreduce.py)."""
    if allreduce_dtype not in ("float32", "bfloat16"):
        raise ValueError(f"unknown grad_allreduce_dtype {allreduce_dtype!r}")
    if not jnp.issubdtype(dtype, jnp.floating):
        return dtype  # exact-sum leaves: never cast
    if allreduce_dtype == "bfloat16":
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(dtype)  # float32 policy: the leaf's own dtype


class _LeafPlan:
    __slots__ = ("index", "shape", "size", "dtype", "is_float", "k")

    def __init__(self, index, shape, dtype, is_float, k=0):
        self.index = index
        self.shape = tuple(shape)
        self.size = int(math.prod(shape)) if shape else 1
        self.dtype = jnp.dtype(dtype)
        self.is_float = is_float
        self.k = k


class GradSync:
    """One gradient-sync strategy, built from config + mesh size.

    Usage (what both step builders do):
        gradsync = GradSync(config, mesh.size)
        # inside the shard_map region:
        payload, gs_new, probe = gradsync.region_reduce(grads, gs_state, step)
        # at the outer jit level:
        grads = gradsync.finalize(payload, step)
    """

    def __init__(self, config, mesh_size: int, axes=None, axis_sizes=None):
        self.mode = getattr(config, "grad_sync", "fused")
        if self.mode not in GRAD_SYNC_MODES:
            raise ValueError(
                f"unknown grad_sync {self.mode!r}; choose from {GRAD_SYNC_MODES}"
            )
        self.n = int(mesh_size)
        # the mesh axes the reduce runs over (ISSUE 15): the 1-D data axis
        # by default; the sharded step builders pass the 2-D mesh's
        # (data, fsdp) with per-axis sizes. With BOTH axes > 1 the
        # quantized mode becomes the DynamiQ-style multi-hop reduce:
        # exact psum over the inner (fast, intra-pod) axis, int8/bf16
        # compressed hop over the outer (slow, inter-pod) axis.
        self.axes = tuple(axes) if axes else (DATA_AXIS,)
        if axis_sizes is None:
            axis_sizes = (self.n,) if len(self.axes) == 1 else None
        if len(self.axes) > 1 and axis_sizes is None:
            raise ValueError("multi-axis GradSync needs axis_sizes")
        self.axis_sizes = tuple(int(s) for s in axis_sizes) if axis_sizes \
            else (self.n,)
        if math.prod(self.axis_sizes) != self.n:
            raise ValueError(
                f"axis_sizes {self.axis_sizes} do not multiply to the mesh "
                f"size {self.n}"
            )
        self.multihop = (
            self.mode == "quantized"
            and len(self.axes) == 2
            and all(s > 1 for s in self.axis_sizes)
        )
        self.allreduce_dtype = getattr(config, "grad_allreduce_dtype", "float32")
        if self.mode in ("fused", "bucketed"):
            # validate at build time, not first trace
            leaf_wire_dtype(jnp.float32, self.allreduce_dtype)
        self.bucket_bytes = int(
            float(getattr(config, "grad_sync_bucket_mb", 4.0)) * 2**20
        )
        self.quant_dtype = getattr(config, "grad_sync_quant_dtype", "int8")
        if self.mode == "quantized" and self.quant_dtype not in ("int8", "bfloat16"):
            raise ValueError(
                f"unknown grad_sync_quant_dtype {self.quant_dtype!r}; "
                "choose int8 or bfloat16"
            )
        self.cadence = int(getattr(config, "grad_sync_cadence", 1))
        self.topk = float(getattr(config, "grad_sync_topk", 0.01))
        self.demo_beta = float(getattr(config, "grad_sync_demo_beta", 0.9))
        self._plans: list[_LeafPlan] | None = None
        self._treedef = None

    @classmethod
    def for_mesh(cls, config, mesh):
        """The strategy bound to `mesh`'s OWN axes — the one constructor
        every consumer of a possibly-2-D mesh must use (step builder,
        driver telemetry, bench rows): a hand-rolled
        `GradSync(config, mesh.size)` on a 2-D mesh would run/describe the
        single-hop reduce while the step executes the multihop one, and
        every byte claim built on it would drift from what P8 audits."""
        axes = tuple(str(a) for a in mesh.axis_names)
        if len(axes) == 1:
            return cls(config, mesh.size)
        return cls(config, mesh.size, axes=axes,
                   axis_sizes=tuple(int(mesh.shape[a]) for a in axes))

    # -- planning (host-side, shapes only) ----------------------------------
    @property
    def needs_state(self) -> bool:
        return self.mode in ("quantized", "demo")

    def plan(self, tree) -> None:
        """Record per-leaf shapes/dtypes (and demo top-k sizes) from a
        grads-shaped tree; pure host arithmetic, safe on tracers."""
        leaves, treedef = jax.tree.flatten(tree)
        plans = []
        for i, leaf in enumerate(leaves):
            is_float = jnp.issubdtype(leaf.dtype, jnp.floating)
            p = _LeafPlan(i, leaf.shape, leaf.dtype, is_float)
            if is_float:
                p.k = max(1, int(math.ceil(p.size * self.topk)))
            plans.append(p)
        self._plans = plans
        self._treedef = treedef

    def _buckets(self) -> list[list[_LeafPlan]]:
        """Size-targeted buckets over the planned leaves, grouped by wire
        dtype, in REVERSE leaf order — backprop materializes the LAST
        layers' grads first, so reverse order approximates readiness order
        and lets early buckets reduce while early layers still backprop.

        Sized by WIRE bytes — what the collective actually carries — so
        `grad_sync_bucket_mb` means the same thing in every mode: a
        quantized int8 bucket packs ~4x the elements of a bucketed-f32 one
        (sizing by f32 bytes would quietly issue 4x more, smaller
        collectives than configured)."""
        buckets: list[list[_LeafPlan]] = []
        cur: list[_LeafPlan] = []
        cur_bytes = 0
        cur_key = None
        for p in reversed(self._plans):
            if self.mode == "quantized" and p.is_float:
                key = (True, self.quant_dtype)
                nbytes = p.size * (1 if self.quant_dtype == "int8" else 2)
            else:
                wire = (
                    leaf_wire_dtype(p.dtype, self.allreduce_dtype)
                    if self.mode == "bucketed"
                    else p.dtype
                )
                key = (p.is_float, str(wire))
                nbytes = p.size * wire.itemsize
            if cur and (key != cur_key or cur_bytes + nbytes > self.bucket_bytes):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += nbytes
            cur_key = key
        if cur:
            buckets.append(cur)
        return buckets

    def describe(self, params) -> dict:
        """Static facts for telemetry/bench: mode, knobs, and the analytic
        per-device sync payload (bytes each device contributes to the wire
        per step, averaged over the demo cadence)."""
        self.plan(params)
        info = {"mode": self.mode,
                "sync_bytes_per_step": self.sync_bytes_per_step()}
        if self.mode in ("bucketed", "quantized"):
            info["bucket_mb"] = round(self.bucket_bytes / 2**20, 3)
            info["buckets"] = len(self._buckets())
        if self.mode == "quantized":
            info["quant_dtype"] = self.quant_dtype
        if self.multihop:
            # per-hop wire accounting (ISSUE 15; progcheck P8 verifies the
            # TOTAL against the traced program): the exact intra hop rides
            # the fast axis, the compressed hop the slow one
            info["multihop"] = {
                "intra_axis": self.axes[1], "intra_size": self.axis_sizes[1],
                "inter_axis": self.axes[0], "inter_size": self.axis_sizes[0],
                "intra_bytes_per_step": self._hop_bytes("intra"),
                "inter_bytes_per_step": self._hop_bytes("inter"),
            }
        if self.mode == "demo":
            info["cadence"] = self.cadence
            info["topk"] = self.topk
        return info

    def _hop_bytes(self, hop: str) -> int:
        """Per-device wire bytes of one multihop-quantized hop: `intra` =
        the exact f32 psum, `inter` = the compressed payload + scales."""
        assert self.multihop and self._plans is not None
        total = 0
        for p in self._plans:
            if not p.is_float:
                continue  # exact-sum leaves ride the single combined psum
            if hop == "intra":
                total += p.size * 4
            else:
                total += p.size * (1 if self.quant_dtype == "int8" else 2)
        if hop == "inter" and self.quant_dtype == "int8":
            total += 4 * sum(1 for p in self._plans if p.is_float)
        return total

    def sync_bytes_per_step(self) -> int:
        """Analytic per-device wire payload per step (see `describe`).
        Multihop quantized counts BOTH hops — the exact intra-pod psum is
        wire traffic too, just on the fast axis."""
        assert self._plans is not None, "call plan()/describe() first"
        total = 0
        for p in self._plans:
            if not p.is_float:
                total += p.size * p.dtype.itemsize
            elif self.mode == "quantized":
                total += p.size * (1 if self.quant_dtype == "int8" else 2)
                if self.multihop:
                    total += p.size * 4  # the exact intra-pod hop
            elif self.mode == "demo":
                # (value f32 + index i32) per selected element, / cadence
                total += int(p.k * 8 / self.cadence)
            else:
                total += p.size * leaf_wire_dtype(
                    p.dtype, self.allreduce_dtype
                ).itemsize
        if self.mode == "quantized" and self.quant_dtype == "int8":
            # one f32 scale per FLOAT LEAF (per-segment scales — see
            # collectives.quantized_psum_mean on scale starvation)
            total += 4 * sum(1 for p in self._plans if p.is_float)
        return total

    # -- state (quantized EF / demo local momentum) --------------------------
    def attach(self, state, mesh):
        """Return `state` with freshly-zeroed gradsync accumulator leaves
        (`[n_dev, *param_shape]`, sharded over the data axis). A no-op tree
        (`{}`) for the stateless modes."""
        if not self.needs_state:
            return state.replace(gradsync={})
        acc = jax.tree.map(
            lambda p: jnp.zeros((mesh.size,) + tuple(p.shape), jnp.float32),
            state.params_q,
        )
        return state.replace(gradsync=self.place_state({STATE_KEY: acc}, mesh))

    def place_state(self, gradsync_tree, mesh):
        """(Re-)place accumulator leaves in the per-device sharded layout —
        applied after a resume, which restores them replicated."""
        from moco_tpu.parallel.zero import shard_pdevice_state

        return shard_pdevice_state(gradsync_tree, mesh)

    # -- region side (inside shard_map over the data axis) -------------------
    def payload_specs(self, P):
        """out_specs prefix for the region payload (`P` is PartitionSpec)."""
        if self.mode == "demo":
            batch = self.reduce_axis
            return {"vals": P(batch), "idx": P(batch), "exact": P()}
        return P()

    @property
    def reduce_axis(self):
        """The axis-name argument the collectives take: the bare name on
        the 1-D mesh (bit-compatible with the pre-ISSUE-15 jaxprs), the
        tuple (one combined device group) on the 2-D mesh."""
        return self.axes[0] if len(self.axes) == 1 else self.axes

    def region_reduce(self, grads, gs_state, step, axis_name=None):
        """Reduce local grads inside the mapped region.

        Returns `(payload, new_gs_state, probe_pre)`:
        - `payload`: the reduced grads tree (fused/bucketed/quantized — typed
          replicated, out_spec P()) or the sparse (vals, idx, exact) trees
          for demo (out_spec per `payload_specs`).
        - `new_gs_state`: the per-device accumulator slices, `[1, ...]` local
          (out_spec P(DATA_AXIS)); `{}` for stateless modes.
        - `probe_pre`: a psum'd scalar depending only on the RAW local grads
          — the "grads are ready" marker the comm-phase fence drains first
          (telemetry/timing.py).
        """
        if axis_name is None:
            axis_name = self.reduce_axis
        self.plan(grads)
        leaves = jax.tree.flatten(grads)[0]
        probe_pre = self._probe_pre(leaves, axis_name)
        if self.mode == "fused":
            return self._reduce_fused(grads, axis_name), {}, probe_pre
        if self.mode == "bucketed":
            return self._reduce_bucketed(leaves, axis_name), {}, probe_pre
        acc_local = [
            a[0].reshape(-1)
            for a in jax.tree.flatten(gs_state[STATE_KEY])[0]
        ] if gs_state else None
        if acc_local is None or len(acc_local) != len(leaves):
            raise ValueError(
                f"grad_sync mode {self.mode!r} needs per-device accumulator "
                "state: call GradSync.attach(state, mesh) after creating the "
                "TrainState (the train driver does this)"
            )
        if self.mode == "quantized":
            return self._reduce_quantized(leaves, acc_local, axis_name)[:2] + (
                probe_pre,
            )
        return self._reduce_demo(leaves, acc_local, step, axis_name) + (probe_pre,)

    def _probe_pre(self, leaves, axis_name):
        for p in self._plans:
            if p.is_float:
                g0 = leaves[p.index].reshape(-1)[0].astype(jnp.float32)
                return lax.psum(g0, axis_name) / self.n
        return jnp.float32(0.0)

    def probe_post(self, grads):
        """Outer-level scalar reading of the REDUCED grads — draining it
        marks "reduce (and merge) finished"."""
        for p in self._plans or ():
            if p.is_float:
                leaf = jax.tree.flatten(grads)[0][p.index]
                return leaf.reshape(-1)[0].astype(jnp.float32)
        return jnp.float32(0.0)

    def _reduce_fused(self, grads, axis_name):
        """The seed `_pmean_grads`, under the explicit per-leaf policy: one
        tree-wide pmean of the float leaves (bitwise the pre-ISSUE-6
        program when everything is f32), exact psum for integer leaves."""
        def down(g):
            return g.astype(leaf_wire_dtype(g.dtype, self.allreduce_dtype))

        if all(p.is_float for p in self._plans):
            reduced = lax.pmean(jax.tree.map(down, grads), axis_name)
            return jax.tree.map(lambda r, g: r.astype(g.dtype), reduced, grads)
        leaves = jax.tree.flatten(grads)[0]
        out = [
            lax.pmean(down(leaves[p.index]), axis_name).astype(p.dtype)
            if p.is_float
            else lax.psum(leaves[p.index], axis_name)
            for p in self._plans
        ]
        return jax.tree.unflatten(self._treedef, out)

    def _reduce_bucketed(self, leaves, axis_name):
        buckets = self._buckets()
        flats = []
        for bucket in buckets:
            segs = [
                leaves[p.index]
                .reshape(-1)
                .astype(leaf_wire_dtype(p.dtype, self.allreduce_dtype))
                for p in bucket
            ]
            flats.append(jnp.concatenate(segs) if len(segs) > 1 else segs[0])
        summed = chained_psum(flats, axis_name)
        out = [None] * len(leaves)
        for bucket, s in zip(buckets, summed):
            red = s / self.n if bucket[0].is_float else s
            off = 0
            for p in bucket:
                out[p.index] = red[off:off + p.size].reshape(p.shape).astype(
                    p.dtype
                )
                off += p.size
        return jax.tree.unflatten(self._treedef, out)

    def _reduce_quantized(self, leaves, acc_local, axis_name):
        buckets = self._buckets()
        out = [None] * len(leaves)
        new_acc = [None] * len(leaves)
        prev = None
        for bucket in buckets:
            if not bucket[0].is_float:
                for p in bucket:
                    out[p.index] = lax.psum(leaves[p.index], axis_name)
                    new_acc[p.index] = acc_local[p.index]
                continue
            segs = [
                (leaves[p.index].reshape(-1).astype(jnp.float32)
                 + acc_local[p.index])
                for p in bucket
            ]
            if prev is not None:
                # sequence the buckets like the bucketed mode: a
                # deterministic issue order the scheduler can pipeline
                segs, prev = optimization_barrier((segs, prev))
            if self.multihop:
                # DynamiQ topology-aware path (2-D mesh, both axes > 1):
                # exact on the fast inner axis, compressed on the slow
                # outer one
                means, errs = multihop_quantized_psum_mean(
                    segs, self.axes[0], self.axes[1],
                    self.axis_sizes[0], self.axis_sizes[1], self.quant_dtype,
                )
            else:
                means, errs = quantized_psum_mean(
                    segs, axis_name, self.n, self.quant_dtype
                )
            prev = means[0]
            for p, mean, err in zip(bucket, means, errs):
                out[p.index] = mean.reshape(p.shape).astype(p.dtype)
                new_acc[p.index] = err
        reduced = jax.tree.unflatten(self._treedef, out)
        acc_tree = jax.tree.unflatten(
            self._treedef,
            [a.reshape((1,) + p.shape) for a, p in zip(new_acc, self._plans)],
        )
        return reduced, {STATE_KEY: acc_tree}

    def _reduce_demo(self, leaves, acc_local, step, axis_name):
        fplans = [p for p in self._plans if p.is_float]
        m = [
            self.demo_beta * acc_local[p.index]
            + leaves[p.index].reshape(-1).astype(jnp.float32)
            for p in fplans
        ]

        def sync_branch(ms):
            vals, idxs, residue = [], [], []
            for p, mm in zip(fplans, ms):
                _, i = lax.top_k(jnp.abs(mm), p.k)
                v = mm[i]
                vals.append(v)
                idxs.append(i.astype(jnp.int32))
                # decouple: the transmitted component leaves the local
                # momentum; the residue keeps accumulating
                residue.append(mm.at[i].add(-v))
            return vals, idxs, residue

        def skip_branch(ms):
            return (
                [jnp.zeros((p.k,), jnp.float32) for p in fplans],
                [jnp.zeros((p.k,), jnp.int32) for p in fplans],
                ms,
            )

        if self.cadence <= 1 or not fplans:
            vals, idxs, residue = sync_branch(m)
        else:
            vals, idxs, residue = lax.cond(
                step % self.cadence == 0, sync_branch, skip_branch, m
            )
        exact = [
            lax.psum(leaves[p.index], axis_name)
            for p in self._plans
            if not p.is_float
        ]
        new_acc = [None] * len(self._plans)
        fi = 0
        for p in self._plans:
            if p.is_float:
                new_acc[p.index] = residue[fi].reshape((1,) + p.shape)
                fi += 1
            else:
                new_acc[p.index] = jnp.zeros((1,) + p.shape, jnp.float32)
        payload = {
            "vals": [v[None] for v in vals],
            "idx": [i[None] for i in idxs],
            "exact": exact,
        }
        acc_tree = jax.tree.unflatten(self._treedef, new_acc)
        return payload, {STATE_KEY: acc_tree}

    # -- audit surface (ISSUE 9; tools/progcheck) ----------------------------
    def audit_region_program(self, params, mesh):
        """The gradsync reduce as a STANDALONE region program, for static
        auditing: returns `(fn, args, payload_shape)` where `fn` is the
        shard_map'd `(grads, gs_state, step) -> (payload, new_state)` over
        a grads-shaped tree matching `params`, `args` are abstract
        ShapeDtypeStructs for it, and `payload_shape` is the payload's
        eval_shape (progcheck maps the demo vals/idx leaves to wire bytes
        from it). Tracing this isolates exactly the collectives this
        strategy issues — the wire-bytes check (P8) compares their jaxpr
        payload against `sync_bytes_per_step()`, so the analytic telemetry
        claim is machine-checked instead of trusted."""
        import jax
        from jax.sharding import PartitionSpec as P

        from moco_tpu.utils.compat import shard_map

        self.plan(params)

        def region(grads, gs_state, step):
            payload, new_state, _probe = self.region_reduce(
                grads, gs_state, step
            )
            return payload, new_state

        state_spec = P(self.reduce_axis) if self.needs_state else P()
        fn = shard_map(
            region, mesh=mesh,
            in_specs=(P(), state_spec, P()),
            out_specs=(self.payload_specs(P), state_spec),
        )
        grads_sds = jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(tuple(p.shape), p.dtype), params
        )
        if self.needs_state:
            state_sds = {STATE_KEY: jax.tree.map(
                lambda p: jax.ShapeDtypeStruct(
                    (mesh.size,) + tuple(p.shape), jnp.float32
                ),
                params,
            )}
        else:
            state_sds = {}
        args = (grads_sds, state_sds, jax.ShapeDtypeStruct((), jnp.int32))
        payload_shape = jax.eval_shape(fn, *args)[0]
        return fn, args, payload_shape

    # -- outer side (replicated merge; jit level, no manual axes) ------------
    def finalize(self, payload, step):
        """Turn the region payload into the grads tree the optimizer sees.

        Identity for fused/bucketed/quantized. For demo the region returns
        per-device (values, indices) pairs typed varying (the same hybrid
        split the queue/EMA updates use — collectives.py replication note),
        so the replicated merge happens HERE at the outer jit level: the
        partitioner's all-gather of the small [n_dev, k] pairs is the only
        communication, and only inside the taken cadence branch."""
        if self.mode != "demo":
            return payload
        assert self._plans is not None, "region_reduce must trace first"
        fplans = [p for p in self._plans if p.is_float]

        def merge(sp):
            vals, idxs = sp
            out = []
            for p, v, i in zip(fplans, vals, idxs):
                flat = (
                    jnp.zeros((p.size,), jnp.float32)
                    .at[i.reshape(-1)]
                    .add(v.reshape(-1))
                    / self.n
                )
                out.append(flat.reshape(p.shape).astype(p.dtype))
            return out

        def zeros(sp):
            return [jnp.zeros(p.shape, p.dtype) for p in fplans]

        if self.cadence <= 1 or not fplans:
            deltas = merge((payload["vals"], payload["idx"]))
        else:
            deltas = lax.cond(
                step % self.cadence == 0, merge, zeros,
                (payload["vals"], payload["idx"]),
            )
        out = [None] * len(self._plans)
        fi = ei = 0
        for p in self._plans:
            if p.is_float:
                out[p.index] = deltas[fi]
                fi += 1
            else:
                out[p.index] = payload["exact"][ei]
                ei += 1
        return jax.tree.unflatten(self._treedef, out)
