"""FSDP parameter/optimizer sharding for the queue-free v3 step (ISSUE 15).

MoCo v3 drops the queue and pays with a ViT backbone at large global batch
— the regime where pure data parallelism runs out: params + optimizer
state replicated per chip cap the model size. This module shards BOTH over
the `fsdp` mesh axis behind `PretrainConfig.sharding`:

  dp       — the seed layout: 1-D mesh, everything replicated. Bitwise the
             pre-ISSUE-15 program.
  fsdp     — 2-D (data=1, fsdp=N) mesh: every device holds 1/N of each
             param/optimizer leaf; the step all-gathers params ON USE
             inside the shard_map region (forward+backward run on the full
             weights, which XLA frees after use) and the reduced gradient
             is SLICED back to the shard (psum + slice == reduce-scatter,
             spelled so the adds happen in exactly the dp order — the
             bitwise-parity anchor tests/test_fsdp.py pins).
  fsdp_tp  — 2-D hybrid (data=M, fsdp=K, M·K=N): params shard over the
             INNER fsdp axis (fast intra-pod ICI on real hardware) and
             replicate over the outer data axis (slow inter-pod DCN), so
             param gathers never cross the slow links; the grad reduce
             spans both axes, and grad_sync=quantized upgrades to the
             DynamiQ-style multi-hop reduce (exact intra, compressed
             inter — collectives.multihop_quantized_psum_mean).

Layout choice: each leaf keeps its LOGICAL shape and shards its largest
fsdp-divisible axis (the `zero.opt_state_shardings` rule, pointed at the
fsdp axis); leaves with no divisible axis (biases, LayerNorm scales, cls
token) stay replicated — they are a rounding error of a ViT's bytes. This
is what makes the checkpoint dialect trivial-by-construction: on disk a
sharded state is the SAME logical tree as a dp state (dialect 3,
checkpoint.TRAIN_STATE_DIALECTS), so dp→fsdp, fsdp→dp and N→M resizes are
ordinary restores into a different placement — no resharding pass, no
silent slicing. Only the gradsync error-feedback accumulators are
layout-bound ([n_dev, ...]), and those restart fresh-zero through the
PR 11 shim (plus the driver's sharding-mode sidecar check).

The optimizer runs at the outer jit level on the sharded leaves: SGD/AdamW
are elementwise, so the partitioner computes each shard locally —
per-element math identical to dp (LARS's per-leaf norms reduce across
shards; same values, float-reduction order aside). The EMA update is
elementwise too, so params_k shards the same way for free.
"""

from __future__ import annotations

import jax
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from moco_tpu.parallel.mesh import FSDP_AXIS, batch_axes


class ShardingPlan:
    """The per-leaf sharding decisions for one (config, mesh) pair.

    Host-side and shape-driven only — safe on abstract trees. The step
    builder derives axis trees from an example state ONCE and closes over
    them; the region's gather/scatter and the outer placement/restore all
    consult the same decisions, so they can never disagree.
    """

    def __init__(self, mode: str, mesh):
        if FSDP_AXIS not in mesh.shape:
            raise ValueError(
                f"sharding={mode!r} needs the 2-D mesh (axes "
                f"{tuple(mesh.axis_names)} lack {FSDP_AXIS!r}) — build it "
                "with mesh_for_config/create_mesh_2d"
            )
        self.mode = mode
        self.mesh = mesh
        self.n_shard = int(mesh.shape[FSDP_AXIS])
        self.batch_axes = batch_axes(mesh)

    # -- per-leaf decisions (shapes only) ------------------------------------
    def leaf_axis(self, shape) -> int | None:
        """The axis this leaf shards over the fsdp axis: its LARGEST
        n_shard-divisible dim, None when no dim divides (replicated)."""
        best = None
        for ax, s in enumerate(shape):
            if s > 0 and s % self.n_shard == 0:
                if best is None or s > shape[best]:
                    best = ax
        return best

    def axis_tree(self, tree):
        """Tree of per-leaf shard-axis indices (None = replicated)."""
        return jax.tree.map(
            lambda leaf: self.leaf_axis(getattr(leaf, "shape", ())), tree
        )

    def _spec(self, axis: int | None):
        if axis is None:
            return P()
        parts = [None] * axis + [FSDP_AXIS]
        return P(*parts)

    def specs(self, tree):
        """PartitionSpec tree (shard_map in/out_specs for a param tree)."""
        return jax.tree.map(
            lambda leaf: self._spec(self.leaf_axis(getattr(leaf, "shape", ()))),
            tree,
        )

    def shardings(self, tree):
        """NamedSharding tree for outer-level placement / Orbax restore."""
        return jax.tree.map(lambda spec: NamedSharding(self.mesh, spec),
                            self.specs(tree))

    def place(self, tree):
        """device_put a (concrete) tree into its sharded placement."""
        return jax.tree.map(jax.device_put, tree, self.shardings(tree))

    # -- region side (inside shard_map) --------------------------------------
    def gather(self, tree, axis_tree):
        """All-gather-on-use: reconstruct full leaves from fsdp shards.
        `axis_tree` must come from `axis_tree()` over the FULL-shape tree
        (the region only sees shard shapes)."""

        def g(leaf, axis):
            if axis is None:
                return leaf
            return lax.all_gather(leaf, FSDP_AXIS, axis=axis, tiled=True)

        return jax.tree.map(g, tree, axis_tree)

    def scatter(self, tree, axis_tree):
        """Slice REDUCED full-shape leaves back to this device's shard —
        psum + slice, the reduce-scatter spelled in the dp adds order."""
        idx = lax.axis_index(FSDP_AXIS)

        def s(leaf, axis):
            if axis is None:
                return leaf
            size = leaf.shape[axis] // self.n_shard
            return lax.dynamic_slice_in_dim(leaf, idx * size, size, axis=axis)

        return jax.tree.map(s, tree, axis_tree)


def plan_for(config, mesh) -> ShardingPlan | None:
    """The config's plan, or None for plain dp."""
    mode = getattr(config, "sharding", "dp")
    if mode == "dp":
        return None
    return ShardingPlan(mode, mesh)


def state_shardings(state, mesh, config):
    """NamedSharding tree for a full TrainState under `config.sharding` —
    the restore target for `restore_checkpoint(sharding=...)` and the
    placement `place_state` applies. params/opt_state follow the per-leaf
    fsdp rule, the gradsync accumulators keep their [n_dev, ...] leading-
    axis split (zero.pdevice_state_shardings), everything else (step,
    batch stats, rng, queue) is replicated."""
    from moco_tpu.parallel.zero import pdevice_state_shardings

    plan = plan_for(config, mesh)
    repl = NamedSharding(mesh, P())

    def replicated_like(tree):
        return jax.tree.map(lambda _: repl, tree)

    if plan is None:
        sharded = replicated_like
    else:
        sharded = plan.shardings
    return state.replace(
        step=repl,
        params_q=sharded(state.params_q),
        params_k=sharded(state.params_k),
        batch_stats_q=replicated_like(state.batch_stats_q),
        batch_stats_k=replicated_like(state.batch_stats_k),
        opt_state=sharded(state.opt_state),
        queue=repl if state.queue is not None else None,
        queue_ptr=repl if state.queue_ptr is not None else None,
        rng=repl,
        gradsync=pdevice_state_shardings(state.gradsync, mesh),
    )


def place_state(state, mesh, config):
    """Place a (freshly-created or just-restored) TrainState per the
    config's sharding: the fsdp analogue of `zero.shard_opt_state` +
    `GradSync.place_state`, in one pass."""
    return jax.tree.map(
        jax.device_put, state, state_shardings(state, mesh, config)
    )


def state_bytes_per_device(state) -> dict:
    """Measured per-device bytes of params_q/params_k/opt_state from the
    leaves' OWN addressable shards (device 0) — the inventory the
    telemetry `sharding` event and the acceptance gate read; under fsdp it
    comes out ~1/N of the dp figure. Replicated leaves (no sharding
    attribute, or fully-replicated placement) count at full size."""

    def tree_bytes(tree) -> int:
        total = 0
        for leaf in jax.tree.leaves(tree):
            if hasattr(leaf, "addressable_shards") and leaf.addressable_shards:
                shard = leaf.addressable_shards[0]
                total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
            elif hasattr(leaf, "nbytes"):
                total += int(leaf.nbytes)
        return total

    params_b = tree_bytes(state.params_q) + tree_bytes(state.params_k)
    opt_b = tree_bytes(state.opt_state)
    return {
        "param_bytes_per_device": params_b,
        "opt_bytes_per_device": opt_b,
        "state_bytes_per_device": params_b + opt_b,
    }
