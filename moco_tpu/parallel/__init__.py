from moco_tpu.parallel.mesh import (
    DATA_AXIS,
    FSDP_AXIS,
    SHARDING_MODES,
    batch_axes,
    create_mesh,
    create_mesh_2d,
    force_cpu_devices,
    local_batch_size,
    distributed_init,
    mesh_for_config,
)
from moco_tpu.parallel.collectives import (
    all_gather_batch,
    batch_axis_index,
    batch_axis_size,
    batch_shuffle,
    batch_unshuffle,
    chained_psum,
    multihop_quantized_psum_mean,
    quantized_psum_mean,
)
from moco_tpu.parallel.gradsync import GRAD_SYNC_MODES, GradSync
from moco_tpu.parallel.fsdp import ShardingPlan, plan_for

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "SHARDING_MODES",
    "batch_axes",
    "create_mesh",
    "create_mesh_2d",
    "force_cpu_devices",
    "local_batch_size",
    "distributed_init",
    "mesh_for_config",
    "all_gather_batch",
    "batch_axis_index",
    "batch_axis_size",
    "batch_shuffle",
    "batch_unshuffle",
    "chained_psum",
    "multihop_quantized_psum_mean",
    "quantized_psum_mean",
    "GRAD_SYNC_MODES",
    "GradSync",
    "ShardingPlan",
    "plan_for",
]
