from moco_tpu.parallel.mesh import (
    DATA_AXIS,
    create_mesh,
    force_cpu_devices,
    local_batch_size,
    distributed_init,
)
from moco_tpu.parallel.collectives import (
    all_gather_batch,
    batch_shuffle,
    batch_unshuffle,
    chained_psum,
    quantized_psum_mean,
)
from moco_tpu.parallel.gradsync import GRAD_SYNC_MODES, GradSync

__all__ = [
    "DATA_AXIS",
    "create_mesh",
    "force_cpu_devices",
    "local_batch_size",
    "distributed_init",
    "all_gather_batch",
    "batch_shuffle",
    "batch_unshuffle",
    "chained_psum",
    "quantized_psum_mean",
    "GRAD_SYNC_MODES",
    "GradSync",
]
