"""Full kNN evaluation on frozen features (BASELINE config 4; SURVEY §2.5,
§3.3 — InstDisc protocol: top-200 cosine neighbors, votes weighted
exp(sim/0.07)).

Pipeline (all on device): encode the ENTIRE train set with the frozen query
encoder into an L2-normalized bank, then score every val image by one
`[B, dim] x [N_bank, dim]^T` matmul + `top_k` + weighted class vote. Unlike
the linear probe this has zero trainable parameters.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.config import EvalConfig
from moco_tpu.data import augment_batch, build_dataset, eval_aug_config
from moco_tpu.evals.lincls import _val_split, load_frozen_backbone
from moco_tpu.ops.knn import knn_accuracy


def build_feature_fn(model):
    """The frozen-encoder eval program: eval-mode forward + L2 norm, jitted
    once and reused across batches (the during-training kNN monitor passes
    it back in). Module-level so tools/progcheck can audit the SAME
    program the evals run (ISSUE 9)."""

    @jax.jit
    def feature_fn(params, stats, images):
        out = model.apply(
            {"params": params, "batch_stats": stats}, images, train=False
        )
        return out / jnp.linalg.norm(out, axis=-1, keepdims=True)

    return feature_fn


def encode_dataset(
    model,
    params,
    stats,
    dataset,
    config,
    batch: int = 256,
    indices: np.ndarray | None = None,
    feature_fn=None,
    mesh=None,
):
    """L2-normalized frozen-encoder features (center-crop transform,
    eval-mode BN) for `dataset` (or a subset via `indices`); the tail chunk
    is padded so the forward compiles once. Pass a precompiled `feature_fn`
    (signature `(params, stats, images)`) to reuse a jit cache across calls —
    the during-training kNN monitor does."""
    from moco_tpu.data.augment import default_eval_crop_frac

    cfg = eval_aug_config(
        config.image_size, crop_frac=default_eval_crop_frac(config.image_size)
    )
    key = jax.random.key(0)

    if feature_fn is None:
        feature_fn = build_feature_fn(model)

    sharding = None
    if mesh is not None and mesh.size > 1:
        # multi-chip eval: shard each batch over the data axis so the eval
        # forward parallelizes under the automatic partitioner (the eval
        # transform has no blur, so no pallas-partitioning caveats apply);
        # round the batch up to a mesh multiple so the shards are even
        from moco_tpu.parallel.mesh import batch_sharded

        sharding = batch_sharded(mesh)
        batch = ((batch + mesh.size - 1) // mesh.size) * mesh.size

    if indices is None:
        indices = np.arange(len(dataset))
    feats, labels = [], []
    from moco_tpu.data.loader import stage_eval_batch

    for start in range(0, len(indices), batch):
        idx = indices[start : start + batch]
        imgs, lbls, extents = stage_eval_batch(
            dataset.get_batch(idx), batch, sharding
        )
        valid = len(idx)
        images = augment_batch(imgs, key, cfg, extents)
        feats.append(np.asarray(feature_fn(params, stats, images))[:valid])
        labels.append(lbls)
    return np.concatenate(feats), np.concatenate(labels)


def run_knn(config: EvalConfig, mesh=None) -> float:
    from moco_tpu.parallel.mesh import create_mesh

    if mesh is None:
        mesh = create_mesh()
    model, params, stats = load_frozen_backbone(config)
    train_set = build_dataset(
        config.dataset, config.data_dir, image_size=config.image_size,
        stage_size=config.stage_size, num_workers=config.num_workers,
    )
    val_set = _val_split(config, train_set)
    bank, bank_labels = encode_dataset(model, params, stats, train_set, config, mesh=mesh)
    queries, qlabels = encode_dataset(model, params, stats, val_set, config, mesh=mesh)
    acc = knn_accuracy(
        jnp.asarray(queries),
        jnp.asarray(qlabels),
        jnp.asarray(bank),
        jnp.asarray(bank_labels),
        num_classes=config.num_classes,
        k=config.knn_k,
        temperature=config.knn_temperature,
        bank_chunk=config.knn_bank_chunk or None,
    )
    from moco_tpu.utils.logging import info

    info(f"kNN top-1: {100 * acc:.2f}% (k={config.knn_k}, T={config.knn_temperature})")
    return acc


def main(argv=None):
    from moco_tpu.config import PRESETS, add_config_flags, collect_overrides, get_preset

    parser = argparse.ArgumentParser(description="moco_tpu kNN evaluation")
    eval_presets = sorted(
        n for n, c in PRESETS.items() if isinstance(c, EvalConfig)
    )
    parser.add_argument("--preset", default="imagenet-lincls", choices=eval_presets)
    add_config_flags(parser, EvalConfig)
    parser.add_argument("--fake-devices", type=int, default=0)
    args = parser.parse_args(argv)
    if args.fake_devices:
        from moco_tpu.parallel.mesh import force_cpu_devices

        force_cpu_devices(args.fake_devices)
    run_knn(get_preset(args.preset).replace(**collect_overrides(args, EvalConfig)))


if __name__ == "__main__":
    main()
