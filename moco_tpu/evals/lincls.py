"""Linear probe on frozen features (layer L4; rebuild of `main_lincls.py` —
the driver behind the 67.5% north-star metric).

Reference semantics reproduced exactly (SURVEY §2.4, §3.2):
- checkpoint surgery: keep `module.encoder_q.*` backbone weights, DROP the
  contrastive head, assert the only missing params are the new classifier
  (`main_lincls.py:≈L176-200`);
- classifier init `fc.weight ~ N(0, 0.01)`, `fc.bias = 0` (`≈L150-175`);
- only 2 trainable tensors — SGD(lr 30, momentum .9, wd 0), x0.1 at epochs
  60/80, 100 epochs (`≈L40-90`, `≈L205-215`);
- "`model.eval()` during training": the frozen backbone runs with BN RUNNING
  stats even on training batches (`≈L300-340`);
- center-crop validation reporting acc1/acc5 (`≈L342-380`);
- `sanity_check`: after training, every backbone weight must be bit-identical
  to the pretrain checkpoint (`≈L390-415`).

TPU shape: features are computed under `stop_gradient` inside the jitted
step; only the classifier sees gradients, so XLA compiles the backbone as
pure inference (no activation stash) and the whole step is one SPMD program
over the data mesh — no parameter-freezing machinery needed.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from moco_tpu.checkpoint import load_for_inference, load_pretrained_backbone
from moco_tpu.config import EvalConfig
from moco_tpu.data import (
    augment_batch,
    build_dataset,
    epoch_loader,
    eval_aug_config,
    v1_aug_config,
)
from moco_tpu.ops.losses import contrastive_accuracy
from moco_tpu.ops.schedules import cosine_lr, step_lr
from moco_tpu.parallel.mesh import create_mesh, local_batch_size
from moco_tpu.utils.logging import info
from moco_tpu.utils.meters import AverageMeter, ProgressMeter


def load_frozen_backbone(config: EvalConfig):
    """Backbone (feature mode) + pretrained weights via checkpoint surgery.

    Thin wrapper over `checkpoint.load_for_inference` — the shared
    dialect-table loader the serve/ subsystem uses too (ISSUE 5), so both
    checkpoint dialects (`module.encoder_q.*` torchvision names and the
    timm fused-qkv / `backbone/*` tree exports) and the surgery's
    exact-backbone-tree check live in exactly one place."""
    return load_for_inference(
        config.pretrained,
        config.arch,
        image_size=config.image_size,
        cifar_stem=config.cifar_stem,
    )


def init_classifier(rng, feat_dim: int, num_classes: int):
    """`fc.weight ~ N(0, 0.01)`, zero bias."""
    w = 0.01 * jax.random.normal(rng, (feat_dim, num_classes), jnp.float32)
    return {"w": w, "b": jnp.zeros((num_classes,), jnp.float32)}


def build_lincls_steps(model, tx):
    """Jitted train/eval steps. Sharding is data-parallel via the automatic
    partitioner (no shard_map needed: BN is frozen, so there are no
    per-device-statistics semantics to preserve — the mesh enters only via
    the input shardings the caller applies to each batch)."""

    def features(params, stats, images):
        # eval-mode BN even while training the probe (`model.eval()`)
        return jax.lax.stop_gradient(
            model.apply({"params": params, "batch_stats": stats}, images, train=False)
        )

    @jax.jit
    def train_step(fc, opt_state, backbone_params, backbone_stats, images, labels):
        feats = features(backbone_params, backbone_stats, images)

        def loss_fn(fc):
            logits = feats @ fc["w"] + fc["b"]
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(fc)
        updates, opt_state = tx.update(grads, opt_state, fc)
        fc = optax.apply_updates(fc, updates)
        acc1, acc5 = contrastive_accuracy(logits, labels)
        return fc, opt_state, {"loss": loss, "acc1": acc1, "acc5": acc5}

    @jax.jit
    def eval_step(fc, backbone_params, backbone_stats, images, labels):
        feats = features(backbone_params, backbone_stats, images)
        logits = feats @ fc["w"] + fc["b"]
        acc1, acc5 = contrastive_accuracy(logits, labels)
        return {
            "correct1": acc1 * labels.shape[0] / 100.0,
            "correct5": acc5 * labels.shape[0] / 100.0,
        }

    return train_step, eval_step


def validate(eval_step, fc, params, stats, dataset, config: EvalConfig, mesh) -> tuple[float, float]:
    """Center-crop validation (`main_lincls.py:≈L342-380`)."""
    from moco_tpu.data.augment import default_eval_crop_frac

    cfg = eval_aug_config(
        config.image_size, crop_frac=default_eval_crop_frac(config.image_size)
    )
    key = jax.random.key(0)
    n = len(dataset)
    b = config.batch_size
    from moco_tpu.parallel.mesh import batch_sharded

    # config.batch_size is mesh-divisible (train_lincls checks local_batch_size)
    sharding = batch_sharded(mesh) if mesh is not None and mesh.size > 1 else None
    c1 = c5 = seen = 0.0
    from moco_tpu.data.loader import stage_eval_batch

    for start in range(0, n, b):
        idx = np.arange(start, min(start + b, n))
        # pad the label tail with -1 (never matches a prediction) so every
        # image is scored and shapes stay fixed
        imgs, labels, extents = stage_eval_batch(
            dataset.get_batch(idx), b, sharding, pad_label=-1
        )
        valid = len(idx)
        images = augment_batch(imgs, key, cfg, extents)
        m = eval_step(fc, params, stats, images, jnp.asarray(labels))
        c1 += float(m["correct1"])
        c5 += float(m["correct5"])
        seen += valid
    return 100.0 * c1 / max(seen, 1), 100.0 * c5 / max(seen, 1)


def sanity_check(params_after, params_pretrained) -> None:
    """Backbone must be untouched after probe training
    (`main_lincls.py:≈L390-415`). strict zip: an empty or mismatched reload
    must fail loudly, not silently compare nothing."""
    leaves_after = jax.tree_util.tree_leaves_with_path(params_after)
    leaves_ref = jax.tree_util.tree_leaves_with_path(params_pretrained)
    if not leaves_ref:
        raise AssertionError("sanity_check got an empty pretrained tree")
    for (pa, a), (pb, b) in zip(leaves_after, leaves_ref, strict=True):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            raise AssertionError(
                f"backbone weight changed during linear probe: {jax.tree_util.keystr(pa)}"
            )


def train_lincls(config: EvalConfig, mesh=None, max_steps: int | None = None):
    """Returns (fc_params, best_acc1). Train transform is the reference's
    supervised stack (random crop + flip); eval is center crop."""
    if mesh is None:
        mesh = create_mesh()
    local_batch_size(config.batch_size, mesh)  # divisibility check

    train_set = build_dataset(
        config.dataset, config.data_dir, image_size=config.image_size,
        stage_size=config.stage_size, num_workers=config.num_workers,
    )
    val_set = _val_split(config, train_set)
    model, backbone_params, backbone_stats = load_frozen_backbone(config)
    # pin the frozen backbone REPLICATED across the mesh once — otherwise the
    # uncommitted host arrays get re-placed on every jitted step
    from moco_tpu.parallel.mesh import replicated

    backbone_params = jax.device_put(backbone_params, replicated(mesh))
    backbone_stats = jax.device_put(backbone_stats, replicated(mesh))

    feat_dim = model.apply(
        {"params": backbone_params, "batch_stats": backbone_stats},
        jnp.zeros((1, config.image_size, config.image_size, 3)),
        train=False,
    ).shape[-1]
    fc = init_classifier(jax.random.key(config.seed), feat_dim, config.num_classes)

    steps_per_epoch = max(len(train_set) // config.batch_size, 1)

    lr = config.effective_lr  # resolves base_lr × batch/256 presets (v3 probe)

    def sched(step):
        epoch = jnp.floor(step / steps_per_epoch)
        if config.cos:
            return cosine_lr(lr, epoch, config.epochs)
        return step_lr(lr, epoch, config.schedule)

    tx = optax.chain(
        optax.add_decayed_weights(config.weight_decay),
        optax.sgd(sched, momentum=config.sgd_momentum),
    )
    opt_state = tx.init(fc)
    train_step, eval_step = build_lincls_steps(model, tx)

    # reference train transform: RandomResizedCrop(scale 0.08-1) + flip
    aug = v1_aug_config(config.image_size)._replace(
        min_scale=0.08, jitter_prob=0.0, grayscale_prob=0.0,
        brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0,
    )
    key = jax.random.key(config.seed + 1)
    best_acc1 = 0.0
    step = 0
    start_epoch = 0
    total = max_steps or config.epochs * steps_per_epoch

    # probe checkpointing (the reference saves fc/optimizer/epoch/best_acc1
    # every epoch and supports --resume, `main_lincls.py:≈L120-140, L280`)
    if config.resume and not config.ckpt_dir:
        raise ValueError("--resume requires a ckpt_dir to resume from")
    mgr = None
    if config.ckpt_dir:
        import orbax.checkpoint as ocp

        from moco_tpu.checkpoint import checkpoint_manager

        mgr = checkpoint_manager(config.ckpt_dir)
        if config.resume == "auto" and mgr.latest_step() is not None:
            probe = {"fc": fc, "opt_state": opt_state,
                     "best_acc1": jnp.zeros(())}
            restored = mgr.restore(
                mgr.latest_step(), args=ocp.args.StandardRestore(probe)
            )
            fc, opt_state = restored["fc"], restored["opt_state"]
            # Orbax restores onto device 0; re-place replicated to match the
            # mesh-replicated backbone
            fc, opt_state = jax.device_put((fc, opt_state), replicated(mesh))
            best_acc1 = float(restored["best_acc1"])
            # epoch-granular resume (reference semantics): a mid-epoch save
            # (max_steps break) resumes from its epoch's START — keeping the
            # raw saved step would skip data and desync the LR schedule
            start_epoch = mgr.latest_step() // steps_per_epoch
            step = start_epoch * steps_per_epoch

    if config.evaluate:
        # reference `-e/--evaluate`: one center-crop validation pass over
        # the (resumed) probe, no training (`main_lincls.py:≈L95, ≈L280`)
        acc1, acc5 = validate(eval_step, fc, backbone_params, backbone_stats,
                              val_set, config, mesh)
        info(f"Evaluate: val Acc@1 {acc1:.2f} Acc@5 {acc5:.2f}")
        return fc, acc1

    for epoch in range(start_epoch, config.epochs):
        losses = AverageMeter("Loss", ":.4e")
        top1 = AverageMeter("Acc@1", ":6.2f")
        progress = ProgressMeter(steps_per_epoch, [losses, top1], f"Epoch: [{epoch}]")
        loader = epoch_loader(train_set, epoch, config.seed, config.batch_size,
                              mesh, depth=config.prefetch_depth,
                              workers=config.staging_workers)
        try:
            for i, (imgs, labels, extents) in enumerate(loader):
                images = augment_batch(
                    imgs, jax.random.fold_in(key, step), aug, extents
                )
                fc, opt_state, metrics = train_step(
                    fc, opt_state, backbone_params, backbone_stats, images, labels
                )
                step += 1
                if i % config.print_freq == 0:
                    losses.update(float(metrics["loss"]), config.batch_size)
                    top1.update(float(metrics["acc1"]), config.batch_size)
                    progress.display(i)
                if step >= total:
                    break
        finally:
            # quietly: a pending staged-read error would mask an in-flight
            # exception here, and the early `step >= total` break makes a
            # stale error for an unconsumed batch possible on success too
            loader.close_quietly()
        acc1, acc5 = validate(eval_step, fc, backbone_params, backbone_stats,
                              val_set, config, mesh)
        best_acc1 = max(best_acc1, acc1)
        info(f"Epoch [{epoch}] val Acc@1 {acc1:.2f} Acc@5 {acc5:.2f} (best {best_acc1:.2f})")
        if mgr is not None:
            import orbax.checkpoint as ocp

            mgr.save(
                step,
                args=ocp.args.StandardSave(
                    {"fc": fc, "opt_state": opt_state,
                     "best_acc1": jnp.asarray(best_acc1)}
                ),
            )
        if step >= total:
            break
    if mgr is not None:
        mgr.wait_until_finished()
    # reference `sanity_check`: reload the pretrain checkpoint from disk and
    # compare (in this functional design the backbone is structurally
    # immutable, but the check still guards against buffer aliasing bugs)
    reloaded, _ = load_pretrained_backbone(
        config.pretrained, num_heads=getattr(model, "num_heads", 12)
    )
    sanity_check(backbone_params, reloaded)
    return fc, best_acc1


def _val_split(config: EvalConfig, train_set=None):
    """Validation dataset: `val/` dir for imagefolder, test split for
    CIFAR-10, a held-out SAME-KIND synthetic set otherwise.

    The synthetic branch must preserve the dataset KIND: the texture
    dataset's class tiles come from a fixed internal seed exactly so a
    different-`seed` instance is a held-out split of the SAME classes
    (datasets.py::SyntheticTextureDataset). Before r5 this fell through
    to `SyntheticDataset` for `synthetic_texture` probes, scoring the
    head against labels from a different generator — the first on-chip
    probe of the gate-passing horizon encoder showed the signature
    (train Acc 99.7%, val Acc 0.39%, BELOW the 6.25% chance) that
    exposed it; that failing log lives in git history (the committed
    runs/lincls_tpu_r5.log is the post-fix 100% run — see
    runs/README.md)."""
    if config.dataset == "imagefolder":
        import os

        return build_dataset(
            "imagefolder", os.path.join(config.data_dir, "val"),
            image_size=config.image_size,
            stage_size=config.stage_size, num_workers=config.num_workers,
        )
    if config.dataset == "cifar10":
        from moco_tpu.data.datasets import CIFAR10

        return CIFAR10(config.data_dir, train=False)
    if config.dataset == "synthetic_texture":
        from moco_tpu.data.datasets import SyntheticTextureDataset

        # label space must MATCH the train split, which train_lincls
        # builds with the dataset's own default class count — deriving
        # from config.num_classes (1000 on the imagenet presets) would
        # recreate the exact train/val label mismatch this branch fixes
        # (review, r5); same convention as train.py::_monitor_val_split
        train_nc = getattr(train_set, "num_classes", None)
        kw = {"num_classes": train_nc} if train_nc else {}
        return SyntheticTextureDataset(
            num_samples=512, image_size=config.image_size, seed=999, **kw)
    from moco_tpu.data.datasets import SyntheticDataset

    return SyntheticDataset(num_samples=512, image_size=config.image_size, seed=999)


def main(argv=None):
    from moco_tpu.config import PRESETS, add_config_flags, collect_overrides, get_preset

    parser = argparse.ArgumentParser(description="moco_tpu linear probe")
    eval_presets = sorted(
        n for n, c in PRESETS.items() if isinstance(c, EvalConfig)
    )
    parser.add_argument("--preset", default="imagenet-lincls", choices=eval_presets)
    add_config_flags(parser, EvalConfig)
    parser.add_argument("--max-steps", type=int, default=None)
    parser.add_argument("--fake-devices", type=int, default=0)
    args = parser.parse_args(argv)
    if args.fake_devices:
        from moco_tpu.parallel.mesh import force_cpu_devices

        force_cpu_devices(args.fake_devices)
    config = get_preset(args.preset).replace(**collect_overrides(args, EvalConfig))
    info(f"config: {config}")
    _, best = train_lincls(config, max_steps=args.max_steps)
    info(f"best val Acc@1: {best:.2f}")


if __name__ == "__main__":
    main()
