from moco_tpu.evals.lincls import train_lincls, load_frozen_backbone, sanity_check
from moco_tpu.evals.knn import run_knn, encode_dataset

__all__ = [
    "train_lincls",
    "load_frozen_backbone",
    "sanity_check",
    "run_knn",
    "encode_dataset",
]
