"""The MoCo pretrain step as ONE jitted SPMD program (SURVEY §7 design stance).

Rebuilds the whole per-step pipeline of `main_moco.py:≈L280-320` +
`MoCo.forward` (`moco/builder.py:≈L117-165`) as a single donated-state jit:

    outer jit level (replicated state, automatic partitioner):
        EMA key-encoder update  (BEFORE the key forward — ordering invariant)
        optimizer update from psum'd grads
        queue enqueue            (AFTER logits — keys never their own negatives)
    inner shard_map region (per-device semantics over the 1-D data mesh):
        ShuffleBN shuffle → key forward (per-device BN stats) → unshuffle
        query forward + InfoNCE + local grads → pmean (the DDP all-reduce)

The hybrid split exists because replicated-state updates derived from
`all_gather`ed values cannot be typed replicated inside shard_map (see
moco_tpu/parallel/collectives.py); outside, XLA's partitioner keeps them
replicated for free — and the whole thing still compiles to one program.

Per-step collectives (cf. SURVEY §3.1): 2 all-gathers of the local key batch
(shuffle-in, unshuffle) + 1 of the 128-d keys (enqueue) + the gradient sync
(ISSUE 6: `parallel/gradsync.py` — one fused pmean, per-bucket chained
psums, quantized reduce with error feedback, or DeMo-style sparse sync,
selected by `config.grad_sync`) + 1 tiny scalar psum (the comm-phase
grads-ready probe the telemetry fence drains). The reference's rank-0
permutation broadcast and DDP buffer re-broadcast are GONE — replaced by
deterministic shared-RNG permutation and replicated arithmetic (zero
communication).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from moco_tpu.utils.compat import optimization_barrier, shard_map

from moco_tpu.config import PretrainConfig
from moco_tpu.models import build_resnet
from moco_tpu.telemetry import health
from moco_tpu.ops.ema import ema_update, momentum_schedule
from moco_tpu.ops.losses import (
    contrastive_accuracy,
    infonce_logits,
    l2_normalize,
    softmax_cross_entropy,
)
from moco_tpu.ops.queue import dequeue_and_enqueue
from moco_tpu.parallel.collectives import batch_shuffle, batch_unshuffle
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.train_state import TrainState


def build_encoder(config: PretrainConfig):
    """Encoder factory — the reference's `models.__dict__[arch](num_classes=dim)`
    plus the v2 MLP-head splice (`moco/builder.py:≈L25-35`). For v3 the
    encoder is backbone→projector (+predictor on the query side), so this
    returns the composite `V3Model`."""
    dtype = jnp.bfloat16 if config.compute_dtype == "bfloat16" else jnp.float32
    if config.variant == "v3":
        from moco_tpu.v3_step import V3Model

        if config.arch.startswith("vit"):
            from moco_tpu.models.vit import build_vit

            backbone = build_vit(
                config.arch, num_classes=None, dtype=dtype, remat=config.remat
            )
        else:
            backbone = build_resnet(
                config.arch,
                num_classes=None,
                cifar_stem=config.cifar_stem,
                dtype=dtype,
                bn_cross_replica_axis=DATA_AXIS if config.sync_bn else None,
                remat=config.remat,
                fused_bn_conv=config.fused_bn_conv,
            )
        return V3Model(backbone, embed_dim=config.embed_dim)
    if config.arch.startswith("vit"):
        from moco_tpu.models.vit import build_vit

        return build_vit(
            config.arch, num_classes=config.embed_dim, dtype=dtype, remat=config.remat
        )
    return build_resnet(
        config.arch,
        num_classes=config.embed_dim,
        mlp_head=config.mlp_head,
        cifar_stem=config.cifar_stem,
        dtype=dtype,
        bn_cross_replica_axis=DATA_AXIS if config.sync_bn else None,
        remat=config.remat,
        fused_bn_conv=config.fused_bn_conv,
    )


def lr_schedule(config: PretrainConfig, steps_per_epoch: int) -> Callable:
    """Step→lr. v1/v2: evaluated at integer epochs (`floor(step/spe)`) to
    match the reference's per-epoch `adjust_learning_rate`
    (`main_moco.py:≈L377-388`). v3: FRACTIONAL epoch — the moco-v3 driver
    adjusts per-iteration (`epoch + i/len(loader)`), and with per-epoch
    stepping the whole first warmup epoch would run at lr=0."""
    from moco_tpu.ops.schedules import cosine_lr, step_lr, warmup_cosine_lr

    lr = config.effective_lr  # resolves base_lr × batch/256 presets

    def sched(step):
        epoch = jnp.asarray(step, jnp.float32) / steps_per_epoch
        if config.variant != "v3":
            epoch = jnp.floor(epoch)
        if config.warmup_epochs > 0:
            return warmup_cosine_lr(lr, epoch, config.epochs, config.warmup_epochs)
        if config.cos:
            return cosine_lr(lr, epoch, config.epochs)
        return step_lr(lr, epoch, config.schedule)

    return sched


def build_optimizer(
    config: PretrainConfig, steps_per_epoch: int
) -> tuple[optax.GradientTransformation, Callable]:
    """The reference's SGD(momentum=0.9, wd=1e-4) with wd folded into the
    momentum buffer (torch semantics: wd enters the gradient BEFORE the
    momentum trace), plus v3's AdamW/LARS options (SURVEY §2.9)."""
    sched = lr_schedule(config, steps_per_epoch)
    if config.optimizer == "sgd":
        tx = optax.chain(
            optax.add_decayed_weights(config.weight_decay),
            optax.sgd(sched, momentum=config.sgd_momentum),
        )
    elif config.optimizer == "adamw":
        tx = optax.adamw(sched, weight_decay=config.weight_decay)
    elif config.optimizer == "lars":
        # moco-v3's LARS (R50 recipe) excludes bias/BN (1-D) params from BOTH
        # weight decay and the trust-ratio adaptation — they get plain
        # momentum SGD at the base lr
        def dim_mask(params):
            return jax.tree.map(lambda p: jnp.ndim(p) > 1, params)

        tx = optax.lars(
            sched,
            weight_decay=config.weight_decay,
            weight_decay_mask=dim_mask,
            trust_ratio_mask=dim_mask,
            momentum=config.sgd_momentum,
        )
    else:
        raise ValueError(f"unknown optimizer {config.optimizer!r}")
    if config.variant == "v3" and config.arch.startswith("vit"):
        # frozen random patch projection: stop_gradient in the model zeroes
        # the grads; the mask stops weight decay from moving the params too
        from moco_tpu.v3_step import patch_embed_trainable_mask

        tx = optax.masked(tx, patch_embed_trainable_mask)
    return tx, sched


def build_fused_step(step_fn, two_crops_fn, data_key):
    """ONE program per step: augmentation + train step in a single donated
    jit. Each program dispatch through the tunneled PJRT relay costs ~4 ms
    (measured r2), so separate aug / fold_in / step programs are pure
    overhead; in-program, XLA also overlaps the aug's VPU work with weight
    prefetches. Shared by the train driver and bench.py so the benchmark
    measures exactly the program training runs."""
    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fused_step(state, imgs_u8, extents, step):
        key = jax.random.fold_in(data_key, step)
        im_q, im_k = two_crops_fn(imgs_u8, key, extents)
        return step_fn(state, im_q, im_k)

    return fused_step


def _build_key_path(config: PretrainConfig, model):
    """The region's key-encoder branch as ONE shared function: ShuffleBN
    shuffle → key forward (per-device BN stats) → unshuffle → L2-norm →
    `stop_gradient` (the reference's no_grad key path, `moco/builder.py`).

    Shared by the spmd_region AND `build_grad_probe` so the audited program
    (progcheck P1: no differentiable path from the loss into the key
    encoder) is the SAME code the train step traces — deleting the
    stop_gradient here changes both, and the auditor fires."""

    chunks = int(getattr(config, "collective_chunks", 1))

    def key_path(params_k, stats_k, im_k, key):
        if config.shuffle_mode == "ring":
            from moco_tpu.parallel.collectives import ring_shuffle

            im_k_shuf = ring_shuffle(im_k, DATA_AXIS)
        else:
            im_k_shuf, perm = batch_shuffle(im_k, key, DATA_AXIS, chunks)
        k, mut_k = model.apply(
            {"params": params_k, "batch_stats": stats_k},
            im_k_shuf,
            train=True,
            mutable=["batch_stats"],
        )
        k = l2_normalize(k)
        if config.shuffle_mode == "ring":
            k = ring_shuffle(k, DATA_AXIS, inverse=True)
        else:
            k = batch_unshuffle(k, perm, DATA_AXIS, chunks)
        k = lax.stop_gradient(k)  # the reference's no_grad key path
        return k, mut_k["batch_stats"]

    return key_path


def _build_query_loss(config: PretrainConfig, model, temperature: float):
    """The region's differentiable core: query forward → InfoNCE against
    (keys, queue). Shared by the spmd_region's value_and_grad and the
    grad-flow probe (which also differentiates w.r.t. the queue)."""

    def query_loss(pq, stats_q, im_q, k, queue):
        q, mut_q = model.apply(
            {"params": pq, "batch_stats": stats_q},
            im_q,
            train=True,
            mutable=["batch_stats"],
        )
        q = l2_normalize(q)
        logits, labels = infonce_logits(q, k, queue, temperature)
        # q rides the aux for the health diagnostics (ISSUE 13) — already
        # computed, and DCE'd by XLA wherever nothing consumes it
        return softmax_cross_entropy(logits, labels), (
            mut_q["batch_stats"],
            logits,
            labels,
            q,
        )

    return query_loss


def build_grad_probe(config: PretrainConfig, model, mesh):
    """The differentiable audit surface (ISSUE 9, tools/progcheck P1).

    Returns a shard_map'd `(params_q, params_k, stats_q, stats_k, queue,
    im_q, im_k, key) -> (g_q, g_k, g_queue)` that differentiates the SAME
    key-path + InfoNCE code the train step traces — w.r.t. the query params
    AND the key params AND the queue. The MoCo contract (He et al.) is that
    the key branch ends in stop_gradient, so `g_k`/`g_queue` must be
    STRUCTURALLY zero: progcheck proves from the jaxpr that those outputs
    depend on no program input, instead of sampling finite differences.
    Grads route through the fused GradSync reduce (lint R7: grads meet
    collectives only via the gradsync API)."""
    from moco_tpu.parallel.gradsync import GradSync

    temperature = config.temperature
    key_path = _build_key_path(config, model)
    query_loss = _build_query_loss(config, model, temperature)
    gradsync = GradSync(config.replace(grad_sync="fused"), mesh.size)

    def probe(params_q, params_k, stats_q, stats_k, queue, im_q, im_k, key):
        def loss_of(pq, pk, qu):
            k, _ = key_path(pk, stats_k, im_k, key)
            loss, _aux = query_loss(pq, stats_q, im_q, k, qu)
            return loss

        grads = jax.grad(loss_of, argnums=(0, 1, 2))(params_q, params_k, queue)
        reduced, _, _probe = gradsync.region_reduce(grads, {}, jnp.int32(0))
        return reduced

    return shard_map(
        probe,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=P(),
    )


def build_train_step(config: PretrainConfig, model, tx, mesh,
                     steps_per_epoch: int, sched=None, state=None):
    """Return jitted `(state, im_q, im_k) -> (state', metrics)`, state donated.

    `im_q`/`im_k` are GLOBAL `[B, H, W, C]` batches (sharded over the data
    axis by the input pipeline); metrics are replicated scalars.

    `sched` must be the schedule returned by `build_optimizer` for the SAME
    `steps_per_epoch` — pass it through so the logged `metrics['lr']` is by
    construction the lr optax applies. If omitted it is re-derived here with
    this call's `steps_per_epoch`.

    `state` (an example TrainState; abstract shapes suffice) is required
    only for the FSDP-sharded v3 step (ISSUE 15) — the per-leaf shard axes
    are fixed from its shapes at build time.
    """
    if config.shuffle_mode not in ("permute", "ring"):
        raise ValueError(f"unknown shuffle_mode {config.shuffle_mode!r}")
    if config.variant == "v3":
        from moco_tpu.v3_step import build_v3_train_step

        return build_v3_train_step(config, model, tx, mesh, steps_per_epoch,
                                   sched, state)

    temperature = config.temperature
    total_steps = config.epochs * steps_per_epoch
    if sched is None:
        sched = lr_schedule(config, steps_per_epoch)
    # gradient sync strategy (ISSUE 6): the ONLY place grads meet a
    # collective — lint R7 forbids pmean/psum on grads outside parallel/
    from moco_tpu.parallel.gradsync import GradSync

    gradsync = GradSync(config, mesh.size)

    # --- ShuffleBN key path + InfoNCE core, factored so build_grad_probe
    # audits exactly this code (ISSUE 9): "permute" = the reference-faithful
    # all-gather + shared-RNG global permutation; "ring" = half-shard roll
    # (2 ppermutes, partial decorrelation — see collectives.ring_shuffle for
    # why whole-shard rotation would be a no-op)
    key_path = _build_key_path(config, model)
    query_loss = _build_query_loss(config, model, temperature)

    def spmd_region(params_q, params_k, stats_q, stats_k, queue, gs_state,
                    im_q, im_k, key, step):
        k, new_stats_k_local = key_path(params_k, stats_k, im_k, key)

        def loss_fn(pq):
            return query_loss(pq, stats_q, im_q, k, queue)

        (loss, (new_stats_q, logits, labels, q)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params_q)
        # DDP-equivalent gradient sync (mean over the data axis) through the
        # configured strategy; demo's replicated merge happens outside
        payload, gs_new, gs_probe = gradsync.region_reduce(grads, gs_state, step)
        # Running BN stats: averaged across devices so replicas stay
        # bit-identical (replaces DDP broadcast_buffers, SURVEY §2.2 note).
        new_stats_q = lax.pmean(new_stats_q, DATA_AXIS)
        new_stats_k = lax.pmean(new_stats_k_local, DATA_AXIS)
        acc1, acc5 = contrastive_accuracy(logits, labels)
        # positive-pair cosine alignment (column 0 is q·k⁺/T): the cheapest
        # honest learning signal — only aug-invariance optimization moves
        # it, so a silently frozen encoder leaves it at its init value
        # while loss/acc metrics can still look plausible against a
        # frozen-feature queue (measured r5, runs/README.md)
        pos_sim = jnp.mean(logits[:, 0]) * temperature
        # the contrast the loss works with (ISSUE 13 standard metrics,
        # popped by the driver like the gs_comm_* probes): a margin
        # pinned at ~0 is collapse or a degenerate queue
        neg_sim = health.neg_sim_mean(logits, labels, temperature)
        metrics = {"loss": loss, "acc1": acc1, "acc5": acc5,
                   "pos_sim": pos_sim, "neg_sim": neg_sim,
                   "logit_margin": pos_sim - neg_sim}
        if config.health_stride:
            # stride-gated collapse diagnostics (ISSUE 13): they join the
            # SAME metrics pmean below — no new collectives
            metrics.update(health.region_health(
                q, k, grads, step, config.health_stride))
        metrics = lax.pmean(metrics, DATA_AXIS)
        return payload, gs_new, gs_probe, k, new_stats_q, new_stats_k, metrics

    region = shard_map(
        spmd_region,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(), P()),
        out_specs=(gradsync.payload_specs(P), P(DATA_AXIS), P(), P(DATA_AXIS),
                   P(), P(), P()),
    )

    def train_step(state: TrainState, im_q, im_k):
        shuffle_key = jax.random.fold_in(state.rng, state.step)
        if config.momentum_ramp:
            m = momentum_schedule(config.momentum_ema, state.step, total_steps)
        else:
            m = config.momentum_ema
        # EMA BEFORE the key forward, every step (`moco/builder.py:≈L120-124`)
        params_k = ema_update(state.params_k, state.params_q, m)
        # barrier: without it XLA interleaves the ~163 per-leaf EMA fusions
        # with the optimizer's per-leaf fusions and the VMEM prefetcher,
        # costing ~20 ms/step of copy stalls on the v5e (measured r2: the
        # update phase alone is 24.8 ms interleaved vs 5.0 ms fenced)
        params_k = optimization_barrier(params_k)
        payload, gs_new, gs_probe, k_global, stats_q, stats_k, metrics = region(
            state.params_q,
            params_k,
            state.batch_stats_q,
            state.batch_stats_k,
            state.queue,
            state.gradsync,
            im_q,
            im_k,
            shuffle_key,
            state.step,
        )
        # demo's sparse merge (a no-op for the dense modes) lives at the
        # outer jit level: replicated values derived from gathered ones
        # cannot be typed replicated inside the region (collectives.py note)
        grads = gradsync.finalize(payload, state.step)
        grads = optimization_barrier(grads)  # fence bwd from the update phase
        updates, opt_state = tx.update(grads, state.opt_state, state.params_q)
        params_q = optax.apply_updates(state.params_q, updates)
        # enqueue AFTER the logits (`moco/builder.py:≈L160-163`)
        queue, queue_ptr = dequeue_and_enqueue(
            state.queue, state.queue_ptr, k_global
        )
        metrics = dict(
            metrics, lr=sched(state.step), queue_ptr=queue_ptr,
            # comm-phase probes (telemetry/timing.py): drained in order by
            # the stride-gated fence, popped by the driver before display
            gs_comm_pre=gs_probe, gs_comm_post=gradsync.probe_post(grads),
        )
        if config.health_stride:
            # replicated-state diagnostics (ISSUE 13) live at the outer
            # jit level where queue/params are replicated: no collective
            metrics.update(health.queue_health(
                state.queue, state.step, config.batch_size,
                config.health_stride))
            metrics.update(health.param_drift(
                state.params_q, params_k, state.step, config.health_stride))
        new_state = state.replace(
            step=state.step + 1,
            params_q=params_q,
            params_k=params_k,
            batch_stats_q=stats_q,
            batch_stats_k=stats_k,
            opt_state=opt_state,
            queue=queue,
            queue_ptr=queue_ptr,
            gradsync=gs_new,
        )
        return new_state, metrics

    return jax.jit(train_step, donate_argnums=(0,))
