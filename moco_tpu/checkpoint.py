"""Checkpoint / resume / export (layer L5; SURVEY §5.4).

Reference behavior being reproduced:
- `save_checkpoint` (`main_moco.py:≈L322-328`): full state every epoch —
  model (INCLUDING queue + pointer buffers), optimizer, epoch. Here the whole
  `TrainState` pytree (queue and ptr included) goes through Orbax, so resume
  is bit-faithful exactly like the reference's `state_dict` round-trip.
- `--resume` (`main_moco.py:≈L190-205`): restore model+optimizer+step.
  TPU-idiomatic extra (SURVEY §5.3): `resume="auto"` restores the latest
  step in the directory, so a preempted TPU VM continues losslessly.
- `detection/convert-pretrain-to-detectron2.py`: the export path. We export
  the QUERY ENCODER with torchvision-style parameter names (the layout the
  reference's checkpoints have under `module.encoder_q.*`) to safetensors /
  npz, so external harnesses (lincls re-runs, Detectron2 converters) can
  consume our checkpoints without JAX (SURVEY §2.6 parity deliverable).
"""

from __future__ import annotations

import json
import os
from typing import TYPE_CHECKING

import jax
import numpy as np

if TYPE_CHECKING:  # annotation-only: see the import note below
    import orbax.checkpoint as ocp

    from moco_tpu.train_state import TrainState

# orbax and TrainState (which drags optax) are imported INSIDE the Orbax
# save/restore functions, not at module level: this module is also the
# inference-side loader (`load_for_inference`, the serve/ path — lint R6
# promises serving processes stay free of the optimizer stack), and the
# flat export/import half needs neither.


# ---------------------------------------------------------------------------
# Orbax save/restore
# ---------------------------------------------------------------------------

# TrainState pytree dialects (ISSUE 6). The tree Orbax sees is versioned by
# STRUCTURE, not a number on disk: dialect 2 adds the optional
# `gradsync/acc` accumulator leaves ([n_dev, *param_shape], per-device
# error-feedback / local-momentum state for grad_sync quantized/demo).
# Fused/bucketed runs carry an EMPTY gradsync subtree (zero array leaves).
# Upgrade (a dialect-1 checkpoint, or one from a different mesh size,
# restored into a dialect-2 target) is handled by `_restore_step`: the
# full-target restore fails, the retry with the gradsync field structurally
# removed succeeds, and the accumulators restart from fresh zeros — always
# convergence-safe (a zero EF/momentum is the cold-start state) at the cost
# of one step's worth of re-accumulated compression error. Downgrade (a
# quantized/demo checkpoint restored by a fused/bucketed run) rides the
# same shim in reverse: the empty-subtree target ignores the on-disk
# accumulators via the stripped retry.
TRAIN_STATE_DIALECTS = {
    1: "pre-gradsync TrainState (PRs 1-5): no gradsync leaves",
    2: "gradsync accumulators: optional gradsync/acc [n_dev, ...] leaves "
       "(grad_sync quantized/demo; empty tree for fused/bucketed)",
    # Dialect 3 (ISSUE 15) is dialect 2 PLUS the sharded-state contract:
    # under sharding=fsdp/fsdp_tp every params/opt leaf keeps its LOGICAL
    # shape (parallel/fsdp.py shards an axis of the same array), so on
    # disk a sharded state is indistinguishable from a dp state and
    # dp→fsdp / fsdp→dp / N→M-device restores are ordinary restores into
    # a different placement (`restore_checkpoint(sharding=<tree>)` — a
    # TrainState-shaped tree of NamedShardings places each leaf directly).
    # Only the gradsync accumulators are layout-bound: mesh-SIZE changes
    # ride the dialect-2 shim below, and sharding-MODE changes at equal
    # mesh size (same acc shapes — structurally invisible here) are
    # caught by the DRIVER against the position sidecar's `sharding`
    # stamp, which zeroes the EF state with a ckpt-dialect event.
    3: "sharded-state (sharding=fsdp/fsdp_tp): same logical tree as 2, "
       "restorable into any placement; `sharding` stamped in the "
       "position sidecar",
}
TRAIN_STATE_DIALECT = 3


def checkpoint_manager(directory: str, max_to_keep: int = 3) -> "ocp.CheckpointManager":
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
    )


def _unkey(state: TrainState) -> TrainState:
    """Typed PRNG keys are not serializable; store the raw uint32 key data."""
    return state.replace(rng=jax.random.key_data(state.rng))


def _rekey(state: TrainState) -> TrainState:
    return state.replace(rng=jax.random.wrap_key_data(state.rng))


def _position_path(directory: str, step: int) -> str:
    # layout shared with the supervisor's quarantine preflight — defined
    # once in the stdlib-only integrity module
    from moco_tpu.resilience.integrity import position_path

    return position_path(directory, step)


def write_position(directory: str, step: int,
                   position: tuple[int, int] | None,
                   devices: int | None = None,
                   sharding: str | None = None) -> None:
    """Record the data-stream position `(epoch, next_batch_index)` the run
    will be at when restored from `step`. `step // steps_per_epoch`
    arithmetic recovers it ONLY while steps and batches are aligned — a NaN
    rollback's data-window skip breaks that permanently, after which a
    resume placed by arithmetic silently replays consumed batches. Written
    atomically on process 0; absent/corrupt sidecars fall back to the
    arithmetic.

    `devices` (the mesh size the state was saved under, ISSUE 11) rides
    the same sidecar so the jax-free supervisor can flag a `mesh_change`
    at relaunch preflight (resize.read_recorded_devices) instead of the
    restore shim discovering it mid-restore. `sharding` (ISSUE 15) records
    the sharding MODE the state was saved under: a mode change is
    structurally invisible to the gradsync shim (acc shapes match at equal
    mesh size), so the driver reads this stamp to know the EF state must
    restart fresh-zero."""
    if position is None or jax.process_index() != 0:
        return
    path = _position_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {"epoch": int(position[0]), "batch": int(position[1])}
    if devices is not None:
        payload["devices"] = int(devices)
    if sharding is not None:
        payload["sharding"] = str(sharding)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def read_position(directory: str, step: int) -> tuple[int, int] | None:
    try:
        with open(_position_path(directory, step)) as f:
            d = json.load(f)
        return int(d["epoch"]), int(d["batch"])
    except (OSError, ValueError, KeyError, TypeError, json.JSONDecodeError):
        return None


def read_recorded_sharding(directory: str, step: int) -> str | None:
    """The sharding mode `step` was saved under (ISSUE 15), None when the
    sidecar predates the stamp (pre-sharding checkpoints — treated as
    'dp' by the driver) or is unreadable."""
    try:
        with open(_position_path(directory, step)) as f:
            d = json.load(f)
        mode = d.get("sharding")
        return str(mode) if mode is not None else None
    except (OSError, ValueError, KeyError, TypeError, json.JSONDecodeError):
        return None


def _prune_sidecars(mgr: ocp.CheckpointManager) -> None:
    """Drop manifest/position sidecars for steps the manager has
    garbage-collected (max_to_keep) — nothing reads them again, and over a
    multi-day run they accumulate without bound."""
    if jax.process_index() != 0:
        return
    keep = {str(s) for s in mgr.all_steps()}
    for sub in (".integrity", ".position"):
        d = os.path.join(str(mgr.directory), sub)
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            stem, ext = os.path.splitext(name)
            if ext == ".json" and stem.isdigit() and stem not in keep:
                try:
                    os.remove(os.path.join(d, name))
                except OSError:
                    pass  # lost a cleanup race; the next prune retries


def save_checkpoint(
    mgr: ocp.CheckpointManager, state: TrainState, step: int, wait: bool = True,
    position: tuple[int, int] | None = None, devices: int | None = None,
    sharding: str | None = None,
) -> None:
    """Save `state` at `step`. With `wait=True` (default), block until the
    save finalizes and record an integrity manifest sidecar (process 0) so a
    later `--resume auto` can walk back past a truncated/partial step
    instead of crashing on it — the right mode for emergency saves (the
    process exits next). With `wait=False` the save stays async so
    serialization overlaps the next epoch's compute, and the manifest is
    DEFERRED: `finalize_checkpoints` (called here on the next save, and by
    the driver at run end / unwind) writes it once Orbax commits. A crash in
    between leaves the step manifest-less, which restore treats as
    unverified-but-restorable — nothing is bricked, that one step just
    loses its cheap integrity gate. `position` (the `(epoch, next_batch)`
    the restored run should resume the data stream at) is recorded as a
    sidecar — see `write_position`."""
    import orbax.checkpoint as ocp

    finalize_checkpoints(mgr)
    write_position(str(mgr.directory), step, position, devices=devices,
                   sharding=sharding)
    mgr.save(step, args=ocp.args.StandardSave(_unkey(state)))
    if wait:
        mgr.wait_until_finished()
        if jax.process_index() == 0:
            from moco_tpu.resilience.integrity import write_manifest

            write_manifest(str(mgr.directory), step)
        _prune_sidecars(mgr)
    else:
        mgr._moco_pending_manifest = step


def finalize_checkpoints(mgr: ocp.CheckpointManager) -> None:
    """Block until any in-flight async save commits, then write its deferred
    integrity manifest. Idempotent; safe on managers with nothing pending."""
    mgr.wait_until_finished()
    step = getattr(mgr, "_moco_pending_manifest", None)
    if step is not None:
        mgr._moco_pending_manifest = None
        if jax.process_index() == 0:
            from moco_tpu.resilience.integrity import write_manifest

            write_manifest(str(mgr.directory), step)
        _prune_sidecars(mgr)


def _restore_step(
    mgr: ocp.CheckpointManager,
    abstract_state: TrainState,
    step: int,
    sharding=None,
) -> TrainState:
    import orbax.checkpoint as ocp

    target = _unkey(abstract_state)
    # `sharding` is one Sharding applied to every leaf (the replicated
    # restore every dp run does), or — ISSUE 15, dialect 3 — a TrainState-
    # shaped TREE of NamedShardings (fsdp: each leaf lands directly in its
    # per-leaf placement; Orbax reads only the shards each host owns).
    leaf_sharding = None   # the per-leaf fallback _restore_fresh_gradsync
    if sharding is not None:  # uses for metadata-rebuilt accumulator leaves
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P

        sharding_is_tree = not isinstance(sharding, jax.sharding.Sharding)

        def to_abstract(x, s):
            x = jnp.asarray(x)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        if sharding_is_tree:
            # leaf-wise zip: the tree mirrors the state's structure (a
            # NamedSharding is itself a leaf, including at the rng slot)
            target = jax.tree.map(to_abstract, target, sharding)
            any_leaf = next(
                (s for s in jax.tree.leaves(
                    sharding, is_leaf=lambda x: isinstance(
                        x, jax.sharding.Sharding))
                 if isinstance(s, NamedSharding)), None)
            # mesh-replicated: metadata-rebuilt gradsync leaves have
            # checkpoint-side shapes a per-leaf plan knows nothing about
            leaf_sharding = (NamedSharding(any_leaf.mesh, _P())
                             if any_leaf is not None else None)
        else:
            target = jax.tree.map(lambda x: to_abstract(x, sharding), target)
            leaf_sharding = sharding
    def _sig(tree):
        return [
            (jax.tree_util.keystr(p), tuple(leaf.shape))
            for p, leaf in jax.tree_util.tree_leaves_with_path(tree)
        ]

    def _restore_fresh_gradsync(md_gs):
        # dialect shim (TRAIN_STATE_DIALECTS): restore with a target whose
        # gradsync subtree is rebuilt FROM THE CHECKPOINT'S OWN metadata —
        # structurally exact, so a healthy checkpoint restores — then
        # throw the on-disk accumulators away and keep the caller's fresh
        # ones (zeros: the convergence-safe cold-start state)
        import dataclasses

        stripped = {
            f.name: getattr(target, f.name)
            for f in dataclasses.fields(target)
            if f.name != "gradsync"
        }
        if md_gs is not None and jax.tree.leaves(md_gs):
            def from_md(m):
                if leaf_sharding is not None:
                    return jax.ShapeDtypeStruct(m.shape, m.dtype,
                                                sharding=leaf_sharding)
                return jax.ShapeDtypeStruct(m.shape, m.dtype)

            stripped["gradsync"] = jax.tree.map(from_md, md_gs)
        restored_dict = mgr.restore(
            step, args=ocp.args.StandardRestore(stripped))
        from moco_tpu.utils.logging import log_event

        log_event(
            "ckpt-dialect",
            f"step {step} has no gradsync accumulators matching this run "
            "(older dialect, different grad_sync mode, or different mesh "
            "size) — restored without them; error-feedback/momentum state "
            "restarts from zeros",
        )
        return type(abstract_state)(
            **{k: v for k, v in restored_dict.items() if k != "gradsync"},
            gradsync=abstract_state.gradsync)

    def _gradsync_md(_require=False):
        # (gradsync metadata, metadata-readable) — `item_metadata` yields
        # None on a manager that has not yet resolved its item handler
        # (a FRESH manager before any save/restore call: every relaunch's
        # `--resume auto`); only a restore attempt registers it. A None
        # here therefore means "unknown", never "no gradsync on disk" —
        # treating it as absent once stripped a key the checkpoint HAS
        # and crash-looped the relaunch on a Dict-key-mismatch.
        try:
            md = mgr.item_metadata(step)
        except Exception:
            if _require:
                raise
            return None, False
        if not isinstance(md, dict):
            return None, False
        return md.get("gradsync"), True

    # the gradsync signature mismatch is checked UP FRONT against the
    # checkpoint's own metadata (when readable), not inferred from a
    # restore failure: on this orbax a mesh-size mismatch ([4, ...]
    # accumulators into a [2, ...] target — the elastic 4→2 relaunch)
    # does NOT fail, it silently SLICES — which would hand the resized
    # run a truncated per-device error-feedback state instead of the
    # fresh-zero cold start the dialect contract promises.
    target_sig = (_sig(getattr(target, "gradsync"))
                  if hasattr(abstract_state, "gradsync") else None)
    if target_sig is not None:
        md_gs, md_known = _gradsync_md()
        if md_known and _sig(md_gs) != target_sig:
            return _rekey(_restore_fresh_gradsync(md_gs))
    try:
        restored = mgr.restore(step, args=ocp.args.StandardRestore(target))
    except Exception:
        # failure-path shim (dialect-1 / mode-switch structure mismatches
        # DO raise, and a fresh manager reaches here with its handler now
        # registered by the failed attempt): same signature test, same
        # stripped retry. A failure with MATCHING signatures is genuine
        # corruption and propagates to the walk-back.
        if target_sig is None:
            raise
        md_gs, md_known = _gradsync_md(_require=True)
        if _sig(md_gs) == target_sig:
            # the checkpoint's gradsync subtree matches the target's — the
            # failure is NOT a dialect/mode/mesh mismatch (transient read,
            # real corruption): re-raise rather than silently zeroing valid
            # on-disk accumulators under a misleading dialect event
            raise
        return _rekey(_restore_fresh_gradsync(md_gs))
    if target_sig is not None:
        # post-restore audit for the fresh-manager path: the successful
        # restore registered the handler, so the metadata is readable NOW
        # — if the on-disk accumulators never matched the target's, the
        # "success" above was orbax's silent slice and the sliced state
        # must be discarded for the fresh-zero cold start
        md_gs, md_known = _gradsync_md()
        if md_known and _sig(md_gs) != target_sig:
            from moco_tpu.utils.logging import log_event

            log_event(
                "ckpt-dialect",
                f"step {step}'s gradsync accumulators do not match this "
                "run's (different mesh size — the restore sliced instead "
                "of failing); discarding them: error-feedback/momentum "
                "state restarts from zeros",
            )
            restored = restored.replace(gradsync=abstract_state.gradsync)
    return _rekey(restored)


def restore_checkpoint(
    mgr: ocp.CheckpointManager,
    abstract_state: TrainState,
    step: int | None = None,
    sharding=None,
) -> TrainState:
    """Restore `step`, or — with `step=None` — the newest step that VERIFIES
    and restores, walking back past corrupt/partial newer ones with a loud
    warning (a preempted writer's half-finished latest step must not brick
    `--resume auto`). An EXPLICIT step still fails hard: the caller asked
    for that step, silently handing back another would be worse than the
    crash. `abstract_state` provides the pytree structure — pass a
    freshly-created state. With `sharding` (e.g. the mesh-replicated
    NamedSharding), Orbax restores DIRECTLY into that placement via
    ShapeDtypeStructs — each host reads its own shards, which is the only
    correct route on multi-process meshes (a restore-then-`device_put`
    would need cross-host transfers)."""
    if step is not None:
        return _restore_step(mgr, abstract_state, step, sharding)
    from moco_tpu.resilience.integrity import verify_step
    from moco_tpu.utils.logging import log_event

    steps = sorted(mgr.all_steps(), reverse=True)
    if not steps:
        raise FileNotFoundError("no checkpoint found to resume from")
    directory = str(mgr.directory)
    if jax.process_count() > 1:
        # Orbax restore of multi-process arrays is COLLECTIVE: hosts making
        # independent verify/fallback decisions desync the pod (host A falls
        # back to an older step while the others' restore of the newer one
        # is in flight — a mismatched collective that hangs or silently
        # yields divergent states). So every decision here is agreed
        # pod-wide: process 0 verifies and broadcasts the candidate order,
        # and after each collective restore ATTEMPT the hosts allgather
        # success — a failure anywhere (e.g. a manifest-less partial step
        # from a mid-save kill, which verifies vacuously) walks ALL hosts
        # back together instead of bricking --resume auto.
        from jax.experimental import multihost_utils

        verdicts = np.zeros(len(steps), np.int64)
        if jax.process_index() == 0:
            for k, s in enumerate(steps):
                reason = verify_step(directory, s)
                verdicts[k] = int(reason is None)
                if reason is not None:
                    log_event(
                        "ckpt-restore",
                        f"step {s} fails integrity check ({reason}); "
                        "falling back to the next-older step",
                    )
        verdicts = np.asarray(multihost_utils.broadcast_one_to_all(verdicts))
        failed: list[int] = []
        for k, s in enumerate(steps):
            if not verdicts[k]:
                failed.append(s)
                continue
            try:
                restored = _restore_step(mgr, abstract_state, s, sharding)
                ok = True
            except Exception as e:  # orbax raises backend-specific types
                log_event(
                    "ckpt-restore",
                    f"restore of step {s} FAILED on this host "
                    f"({type(e).__name__}: {e}); awaiting pod agreement",
                )
                restored, ok = None, False
            all_ok = bool(
                np.min(multihost_utils.process_allgather(np.int64(ok)))
            )
            if all_ok:
                if failed:
                    log_event(
                        "ckpt-restore",
                        f"restored OLDER step {s} after skipping {failed} — "
                        f"up to {steps[0] - s} steps of progress lost",
                    )
                return restored
            failed.append(s)
        raise FileNotFoundError(
            f"no restorable checkpoint in {directory}; all candidates "
            f"failed: {failed}"
        )
    skipped: list[tuple[int, str]] = []
    for s in steps:
        reason = verify_step(directory, s)
        if reason is not None:
            log_event(
                "ckpt-restore",
                f"step {s} fails integrity check ({reason}); "
                "falling back to the next-older step",
            )
            skipped.append((s, reason))
            continue
        try:
            restored = _restore_step(mgr, abstract_state, s, sharding)
        except Exception as e:  # orbax raises backend-specific types
            log_event(
                "ckpt-restore",
                f"restore of step {s} FAILED ({type(e).__name__}: {e}); "
                "falling back to the next-older step",
            )
            skipped.append((s, repr(e)))
            continue
        if skipped:
            log_event(
                "ckpt-restore",
                f"restored OLDER step {s} after skipping "
                f"{[x[0] for x in skipped]} — up to "
                f"{steps[0] - s} steps of progress lost to corrupt saves",
            )
        return restored
    raise FileNotFoundError(
        f"no restorable checkpoint in {directory}; all candidates failed: "
        f"{skipped}"
    )


def maybe_resume(
    mgr: ocp.CheckpointManager, state: TrainState, resume: str, sharding=None
) -> TrainState:
    """`resume == "auto"`: latest if any (fresh state otherwise);
    `resume == ""`: fresh; an integer: that step in `mgr`'s directory; a
    path `<ckpt_dir>/<step>`: that step from that directory (the reference's
    `--resume <path>` contract, `main_moco.py:≈L190-205`)."""
    if not resume:
        return state
    if resume == "auto":
        if mgr.latest_step() is None:
            return state
        return restore_checkpoint(mgr, state, sharding=sharding)
    if resume.isdigit():
        return restore_checkpoint(mgr, state, int(resume), sharding=sharding)
    # path form: .../<ckpt_dir>/<step>
    path = os.path.normpath(resume)
    base = os.path.basename(path)
    if not base.isdigit():
        raise ValueError(
            f"--resume expects 'auto', a step number, or a path ending in a "
            f"step directory; got {resume!r}"
        )
    other = checkpoint_manager(os.path.dirname(path))
    return restore_checkpoint(other, state, int(base), sharding=sharding)


# ---------------------------------------------------------------------------
# torchvision-name export (the reference checkpoint dialect)
# ---------------------------------------------------------------------------


def _bn_entries(prefix: str, params: dict, stats: dict) -> dict[str, np.ndarray]:
    out = {
        f"{prefix}.weight": np.asarray(params["scale"]),
        f"{prefix}.bias": np.asarray(params["bias"]),
    }
    if stats:
        out[f"{prefix}.running_mean"] = np.asarray(stats["mean"])
        out[f"{prefix}.running_var"] = np.asarray(stats["var"])
    return out


def _conv_entry(prefix: str, params: dict) -> dict[str, np.ndarray]:
    # flax [kh, kw, cin, cout] → torch [cout, cin, kh, kw]. Contiguous copy:
    # safetensors serializes the raw buffer and ignores view strides.
    return {
        f"{prefix}.weight": np.ascontiguousarray(
            np.asarray(params["kernel"]).transpose(3, 2, 0, 1)
        )
    }


def _dense_entries(prefix: str, params: dict) -> dict[str, np.ndarray]:
    out = {f"{prefix}.weight": np.ascontiguousarray(np.asarray(params["kernel"]).T)}
    if "bias" in params:
        out[f"{prefix}.bias"] = np.asarray(params["bias"])
    return out


def resnet_to_torchvision(
    params: dict, batch_stats: dict, mlp_head: bool | None = None, prefix: str = ""
) -> dict[str, np.ndarray]:
    """Flatten a moco_tpu ResNet param tree to torchvision state_dict names.

    Name map: `layer{i}_{j}` → `layer{i}.{j}`, `downsample_conv/bn` →
    `downsample.0/1`, v2 MLP head `fc_hidden`/`fc` → `fc.0`/`fc.2` (the
    reference's `Sequential(Linear, ReLU, Linear)` indices). `mlp_head` is
    auto-detected from the tree (presence of `fc_hidden`) unless forced.
    """
    if mlp_head is None:
        mlp_head = "fc_hidden" in params
    stats = batch_stats or {}
    out: dict[str, np.ndarray] = {}
    for name, sub in params.items():
        sub_stats = stats.get(name, {})
        if name == "conv1":
            out.update(_conv_entry(prefix + "conv1", sub))
        elif name == "bn1":
            out.update(_bn_entries(prefix + "bn1", sub, sub_stats))
        elif name.startswith("layer"):
            stage, block = name.split("_")
            bprefix = f"{prefix}{stage}.{block}"
            for lname, lsub in sub.items():
                lstats = sub_stats.get(lname, {})
                if lname.startswith("conv"):
                    out.update(_conv_entry(f"{bprefix}.{lname}", lsub))
                elif lname.startswith("bn"):
                    out.update(_bn_entries(f"{bprefix}.{lname}", lsub, lstats))
                elif lname == "downsample_conv":
                    out.update(_conv_entry(f"{bprefix}.downsample.0", lsub))
                elif lname == "downsample_bn":
                    out.update(_bn_entries(f"{bprefix}.downsample.1", lsub, lstats))
                else:
                    raise ValueError(f"unexpected block member {name}.{lname}")
        elif name == "fc_hidden":
            out.update(_dense_entries(prefix + "fc.0", sub))
        elif name == "fc":
            out.update(
                _dense_entries(prefix + ("fc.2" if mlp_head else "fc"), sub)
            )
        else:
            raise ValueError(f"unexpected top-level module {name}")
    return out


def export_encoder_q(
    state: TrainState,
    path: str,
    mlp_head: bool | None = None,  # auto-detected from the param tree
    prefix: str = "module.encoder_q.",
) -> dict[str, np.ndarray]:
    """Write the query encoder in the reference's checkpoint dialect
    (`module.encoder_q.*`, torchvision tensor layouts) as safetensors (or
    `.npz` if the path says so). Returns the flat dict written."""
    flat = resnet_to_torchvision(
        jax.tree.map(np.asarray, state.params_q),
        jax.tree.map(np.asarray, state.batch_stats_q),
        mlp_head=mlp_head,
        prefix=prefix,
    )
    _save_flat(flat, path)
    return flat


def flatten_tree(tree, prefix: str = "") -> dict[str, np.ndarray]:
    """Generic `a/b/c`-joined flattening (the export dialect for backbones
    with no torchvision equivalent, e.g. the v3 ViT)."""
    out: dict[str, np.ndarray] = {}
    for name, sub in tree.items():
        key = f"{prefix}{name}"
        if isinstance(sub, dict):
            out.update(flatten_tree(sub, key + "/"))
        else:
            out[key] = np.ascontiguousarray(np.asarray(sub))
    return out


def unflatten_tree(flat: dict[str, np.ndarray], prefix: str = "") -> dict:
    tree: dict = {}
    for name, arr in flat.items():
        if not name.startswith(prefix):
            continue
        parts = name[len(prefix):].split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def _save_flat(flat: dict[str, np.ndarray], path: str) -> None:
    """One writer for both export dialects (npz by extension, else safetensors)."""
    if path.endswith(".npz"):
        np.savez(path, **flat)
    else:
        from safetensors.numpy import save_file

        save_file(flat, path)


def export_backbone_tree(
    params: dict, batch_stats: dict, path: str
) -> dict[str, np.ndarray]:
    """Export an arbitrary backbone tree (no torchvision equivalent — ViT
    encoders, v3 backbones) in the `backbone/a/b/c` dialect, with
    `backbone_stats/` for BN running stats."""
    flat = flatten_tree(jax.tree.map(np.asarray, params), "backbone/")
    if batch_stats:
        flat.update(
            flatten_tree(jax.tree.map(np.asarray, batch_stats), "backbone_stats/")
        )
    _save_flat(flat, path)
    return flat


def _vit_grid(params: dict, image_size: int) -> tuple[int, int]:
    """Patch grid for the timm `pos_embed` buffer, from the patch-embed
    kernel's own patch size and the training resolution."""
    p = int(np.asarray(params["patch_embed"]["kernel"]).shape[0])
    return (image_size // p, image_size // p)


def export_v3_backbone(
    state: TrainState, path: str, image_size: int = 224
) -> dict[str, np.ndarray]:
    """MoCo-v3 query BACKBONE export (predictor/projector dropped — the v3
    lincls protocol probes backbone features). ViT backbones are written in
    the PUBLIC timm dialect (`blocks.N.*`) so external harnesses — timm's
    `load_state_dict`, the moco-v3 lincls surgery — can consume a v3
    pretrain directly (VERDICT r1 #6); ResNet v3 backbones keep the tree
    dialect (their public dialect is the v1/v2 `module.encoder_q.*` export,
    which expects the contrastive fc this state doesn't have)."""
    params = state.params_q["backbone"]
    if "patch_embed" in params:  # ViT backbone
        flat = vit_to_timm(
            jax.tree.map(np.asarray, params), grid=_vit_grid(params, image_size)
        )
        _save_flat(flat, path)
        return flat
    return export_backbone_tree(
        params,
        state.batch_stats_q.get("backbone", {}),
        path,
    )


def export_vit_encoder(
    state: TrainState, path: str, image_size: int = 224
) -> dict[str, np.ndarray]:
    """v1/v2 export for ViT encoders: timm dialect for the backbone (public,
    consumable by timm/moco-v3 tooling) with the contrastive `head` dropped."""
    params = {k: v for k, v in state.params_q.items() if k != "head"}
    flat = vit_to_timm(
        jax.tree.map(np.asarray, params), grid=_vit_grid(params, image_size)
    )
    _save_flat(flat, path)
    return flat


# ---------------------------------------------------------------------------
# timm-dialect ViT export (the public ViT checkpoint naming)
# ---------------------------------------------------------------------------


def _sincos_pos_embed_np(gh: int, gw: int, dim: int) -> np.ndarray:
    """timm-style `pos_embed` [1, 1+gh*gw, dim]: zero class-token row +
    the fixed 2-D sin-cos grid (moco-v3's
    `build_2d_sincos_position_embedding` emits exactly this buffer —
    `pe_token = zeros([1,1,D])` concatenated before the grid)."""
    from moco_tpu.models.vit import sincos_2d_position_embedding

    grid = np.asarray(sincos_2d_position_embedding(gh, gw, dim))
    return np.concatenate([np.zeros((1, 1, dim), np.float32), grid], axis=1)


def vit_to_timm(params: dict, prefix: str = "", grid: tuple[int, int] = (14, 14)) -> dict[str, np.ndarray]:
    """Flatten a moco_tpu ViT param tree to timm `VisionTransformer`
    state_dict names (`cls_token`, `pos_embed`, `patch_embed.proj.*`,
    `blocks.N.{norm1,attn.qkv,attn.proj,norm2,mlp.fc1,mlp.fc2}.*`, `norm.*`)
    — the dialect moco-v3's ViT checkpoints speak (its `vits.py` subclasses
    timm's `VisionTransformer`), so exported v3 pretrains are consumable by
    any timm-based harness. `pos_embed` is our fixed sin-cos buffer
    (parameter-free in the model; emitted because the dialect expects it).
    """
    width = int(params["cls_token"].shape[-1])
    out: dict[str, np.ndarray] = {
        f"{prefix}cls_token": np.asarray(params["cls_token"], np.float32),
        f"{prefix}pos_embed": _sincos_pos_embed_np(grid[0], grid[1], width),
    }
    out.update(_conv_entry(f"{prefix}patch_embed.proj", params["patch_embed"]))
    out[f"{prefix}patch_embed.proj.bias"] = np.asarray(params["patch_embed"]["bias"])
    blocks = sorted(
        (int(k[len("block"):]), k) for k in params if k.startswith("block")
    )
    for i, name in blocks:
        blk = params[name]
        bp = f"{prefix}blocks.{i}"
        for ln, tn in (("norm1", "norm1"), ("norm2", "norm2")):
            out[f"{bp}.{tn}.weight"] = np.asarray(blk[ln]["scale"])
            out[f"{bp}.{tn}.bias"] = np.asarray(blk[ln]["bias"])
        attn = blk["attn"]
        # flax q/k/v kernels [D, H, hd] → torch rows h*hd+d: reshape+T;
        # stacked [q;k;v] like timm's fused qkv Linear
        qkv_w = [
            np.ascontiguousarray(np.asarray(attn[m]["kernel"]).reshape(width, width).T)
            for m in ("query", "key", "value")
        ]
        qkv_b = [np.asarray(attn[m]["bias"]).reshape(width) for m in ("query", "key", "value")]
        out[f"{bp}.attn.qkv.weight"] = np.concatenate(qkv_w, axis=0)
        out[f"{bp}.attn.qkv.bias"] = np.concatenate(qkv_b, axis=0)
        out[f"{bp}.attn.proj.weight"] = np.ascontiguousarray(
            np.asarray(attn["out"]["kernel"]).reshape(width, width).T
        )
        out[f"{bp}.attn.proj.bias"] = np.asarray(attn["out"]["bias"])
        out.update(_dense_entries(f"{bp}.mlp.fc1", blk["mlp_fc1"]))
        out.update(_dense_entries(f"{bp}.mlp.fc2", blk["mlp_fc2"]))
    out[f"{prefix}norm.weight"] = np.asarray(params["norm"]["scale"])
    out[f"{prefix}norm.bias"] = np.asarray(params["norm"]["bias"])
    return out


def timm_to_vit(
    flat: dict[str, np.ndarray], num_heads: int = 12, prefix: str = ""
) -> dict:
    """Inverse of `vit_to_timm`: rebuild the flax ViT param tree from a
    timm-dialect checkpoint (ours, or any timm ViT with fused qkv).
    `num_heads` splits the fused qkv back into flax's [D, H, hd] kernels —
    12 for every moco-v3 arch (its `vits.py` uses head dim 32 throughout).
    `head.*` entries are ignored (probe head, not backbone params). A
    `pos_embed` entry is CHECKED against our fixed sin-cos buffer: the flax
    ViT has no positional parameter, so a checkpoint with a LEARNED pos_embed
    would silently run with different positions — that import is refused
    rather than degraded (ADVICE r2)."""
    width = int(flat[f"{prefix}cls_token"].shape[-1])
    pe = flat.get(f"{prefix}pos_embed")
    if pe is not None:
        pe = np.asarray(pe)
        n_patches = pe.shape[-2] - 1
        g = int(round(n_patches ** 0.5))
        expected = (
            _sincos_pos_embed_np(g, g, width)
            if g * g == n_patches
            else None
        )
        if expected is None or not np.allclose(
            pe.reshape(expected.shape), expected, rtol=1e-3, atol=1e-3
        ):
            raise ValueError(
                "timm checkpoint carries a pos_embed that differs from the "
                "fixed 2-D sin-cos buffer this ViT uses (a learned or resized "
                "positional embedding). Importing it would silently change "
                "token positions; convert the checkpoint (or retrain) instead."
            )
    hd = width // num_heads
    tree: dict = {
        "cls_token": np.asarray(flat[f"{prefix}cls_token"]),
        "patch_embed": {
            "kernel": np.asarray(flat[f"{prefix}patch_embed.proj.weight"]).transpose(2, 3, 1, 0),
            "bias": np.asarray(flat[f"{prefix}patch_embed.proj.bias"]),
        },
        "norm": {
            "scale": np.asarray(flat[f"{prefix}norm.weight"]),
            "bias": np.asarray(flat[f"{prefix}norm.bias"]),
        },
    }
    n_blocks = 1 + max(
        int(k[len(prefix):].split(".")[1])
        for k in flat
        if k.startswith(f"{prefix}blocks.")
    )
    for i in range(n_blocks):
        bp = f"{prefix}blocks.{i}"
        qkv_w = np.asarray(flat[f"{bp}.attn.qkv.weight"])
        qkv_b = np.asarray(flat[f"{bp}.attn.qkv.bias"])
        attn: dict = {}
        for j, m in enumerate(("query", "key", "value")):
            w = qkv_w[j * width:(j + 1) * width]  # [D_out, D_in]
            b = qkv_b[j * width:(j + 1) * width]
            attn[m] = {
                "kernel": np.ascontiguousarray(w.T).reshape(width, num_heads, hd),
                "bias": b.reshape(num_heads, hd),
            }
        attn["out"] = {
            "kernel": np.ascontiguousarray(
                np.asarray(flat[f"{bp}.attn.proj.weight"]).T
            ).reshape(num_heads, hd, width),
            "bias": np.asarray(flat[f"{bp}.attn.proj.bias"]),
        }
        tree[f"block{i}"] = {
            "norm1": {
                "scale": np.asarray(flat[f"{bp}.norm1.weight"]),
                "bias": np.asarray(flat[f"{bp}.norm1.bias"]),
            },
            "norm2": {
                "scale": np.asarray(flat[f"{bp}.norm2.weight"]),
                "bias": np.asarray(flat[f"{bp}.norm2.bias"]),
            },
            "attn": attn,
            "mlp_fc1": {
                "kernel": np.ascontiguousarray(np.asarray(flat[f"{bp}.mlp.fc1.weight"]).T),
                "bias": np.asarray(flat[f"{bp}.mlp.fc1.bias"]),
            },
            "mlp_fc2": {
                "kernel": np.ascontiguousarray(np.asarray(flat[f"{bp}.mlp.fc2.weight"]).T),
                "bias": np.asarray(flat[f"{bp}.mlp.fc2.bias"]),
            },
        }
    return tree


# ---------------------------------------------------------------------------
# Checkpoint dialects — the ONE table every non-training consumer routes on
# ---------------------------------------------------------------------------

# name → predicate over the flat key set. Ordered: first match wins. This is
# the single source of truth for "what kind of checkpoint is this" — the
# lincls surgery, the serve/ inference loader, and the Detectron2 converter
# all route through it, so a new dialect lands in exactly one place.
CHECKPOINT_DIALECTS: tuple[tuple[str, object], ...] = (
    # v3 ResNet backbones (tree export; projector/predictor already dropped)
    ("v3_tree", lambda flat: any(k.startswith("backbone/") for k in flat)),
    # timm VisionTransformer names with fused qkv (ours, or any timm ViT)
    ("timm_vit", lambda flat: "patch_embed.proj.weight" in flat),
    # the reference's torchvision dialect (v1/v2 ResNet, `module.encoder_q.*`)
    ("torchvision_encoder_q",
     lambda flat: any(k.startswith("module.encoder_q.") for k in flat)),
)


def detect_dialect(flat: dict[str, np.ndarray]) -> str:
    """Classify a flat checkpoint dict against `CHECKPOINT_DIALECTS`.
    Raises with the known-dialect list on a miss — every consumer used to
    fall through to its own (differently-worded) failure."""
    for name, pred in CHECKPOINT_DIALECTS:
        if pred(flat):
            return name
    known = ", ".join(name for name, _ in CHECKPOINT_DIALECTS)
    raise ValueError(
        f"checkpoint matches no known dialect (looked for: {known}); "
        f"got keys like {sorted(flat)[:3]}"
    )


def load_pretrained_backbone(path: str, num_heads: int = 12) -> tuple[dict, dict]:
    """Dialect-routed load of a pretrained backbone: torchvision
    `module.encoder_q.*` (v1/v2 ResNet, head dropped), timm `blocks.N.*`
    (ViT — ours or any fused-qkv timm checkpoint), or `backbone/*` trees
    (v3 ResNet). Returns (params, batch_stats) as numpy trees."""
    flat = import_encoder_q(path)
    dialect = detect_dialect(flat)
    if dialect == "v3_tree":
        return unflatten_tree(flat, "backbone/"), unflatten_tree(
            flat, "backbone_stats/"
        )
    if dialect == "timm_vit":
        return timm_to_vit(flat, num_heads=num_heads), {}
    return torchvision_to_resnet(flat)


def load_for_inference(
    path: str,
    arch: str,
    *,
    image_size: int = 224,
    cifar_stem: bool = False,
):
    """Checkpoint-surgery restore for every non-training consumer (the
    lincls probe, the serve/ embedding service, detectron2-adjacent
    tooling): build the feature-mode encoder for `arch`, load `path`
    through the dialect table, and verify the surgery yielded EXACTLY the
    backbone tree (the reference asserts missing_keys == {fc.*}; here the
    equivalent is a path-set equality against a fresh init). Returns
    `(model, params, batch_stats)` with the trees as jax arrays.

    ViT archs split the timm fused qkv with THIS arch's head count — a
    wrong count mis-partitions heads silently, which is why consumers must
    not call `load_pretrained_backbone` with a guessed `num_heads`."""
    import jax.numpy as jnp

    from moco_tpu.models import build_backbone

    model = build_backbone(arch, cifar_stem=cifar_stem)
    params, stats = load_pretrained_backbone(
        path, num_heads=getattr(model, "num_heads", 12)
    )
    ref = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0),
            jnp.zeros((1, image_size, image_size, 3)),
            train=False,
        )
    )
    ref_paths = {jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(ref["params"])}
    got_paths = {jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(params)}
    if ref_paths != got_paths:
        missing = sorted(ref_paths - got_paths)[:5]
        extra = sorted(got_paths - ref_paths)[:5]
        raise ValueError(
            f"checkpoint surgery mismatch for arch {arch!r}: "
            f"missing {missing}, extra {extra}"
        )
    return (
        model,
        jax.tree.map(jnp.asarray, params),
        jax.tree.map(jnp.asarray, stats),
    )


def import_encoder_q(path: str) -> dict[str, np.ndarray]:
    """Load a flat exported dict back (for the lincls key-surgery path)."""
    if path.endswith(".npz"):
        return dict(np.load(path))
    from safetensors.numpy import load_file

    return load_file(path)


def torchvision_to_resnet(
    flat: dict[str, np.ndarray], prefix: str = "module.encoder_q."
) -> tuple[dict, dict]:
    """Inverse of `resnet_to_torchvision`: the lincls "checkpoint surgery"
    (`main_lincls.py:≈L176-200`) — keep `<prefix>*` backbone entries, strip
    the prefix, DROP the contrastive head (`fc*`), and rebuild the flax
    `(params, batch_stats)` trees. Consumes our exports and any checkpoint
    flattened to the reference's torchvision dialect."""

    def set_nested(tree, keys, value):
        node = tree
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = value

    params: dict = {}
    stats: dict = {}
    for name, arr in flat.items():
        if not name.startswith(prefix):
            continue
        name = name[len(prefix):]
        parts = name.split(".")
        if parts[0].startswith("fc"):
            continue  # contrastive head: dropped, exactly like the reference
        *mods, leaf = parts
        # normalize module path: downsample.0/.1 → downsample_conv/_bn
        if len(mods) >= 2 and mods[-2] == "downsample":
            mod = "downsample_conv" if mods[-1] == "0" else "downsample_bn"
            mods = mods[:-2] + [mod]
        if len(mods) >= 2 and mods[0].startswith("layer"):
            mods = [f"{mods[0]}_{mods[1]}"] + mods[2:]
        if leaf == "weight":
            if arr.ndim == 4:
                set_nested(params, mods + ["kernel"], arr.transpose(2, 3, 1, 0))
            elif arr.ndim == 2:
                set_nested(params, mods + ["kernel"], arr.T)
            else:  # BN scale
                set_nested(params, mods + ["scale"], arr)
        elif leaf == "bias":
            set_nested(params, mods + ["bias"], arr)
        elif leaf == "running_mean":
            set_nested(stats, mods + ["mean"], arr)
        elif leaf == "running_var":
            set_nested(stats, mods + ["var"], arr)
        elif leaf in ("num_batches_tracked",):
            continue
        else:
            raise ValueError(f"unexpected leaf {name!r}")
    return params, stats
