"""Console metering (rebuild of `AverageMeter`/`ProgressMeter`,
`main_moco.py:≈L330-375`) plus the imgs/sec meter that IS the north-star
throughput metric (BASELINE.md derived-throughput row)."""

from __future__ import annotations

import time


class AverageMeter:
    """Running value/average, printed as `name val (avg)`."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name, self.fmt = name, fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    def __str__(self):
        return ("{name} {val" + self.fmt + "} ({avg" + self.fmt + "})").format(
            name=self.name, val=self.val, avg=self.avg
        )


class ProgressMeter:
    def __init__(self, num_batches: int, meters, prefix: str = ""):
        fmt = "{:" + str(len(str(num_batches))) + "d}"
        self.batch_fmtstr = "[" + fmt + "/" + fmt.format(num_batches) + "]"
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int):
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        print("\t".join(entries), flush=True)


class RateMeter:
    """Cumulative event count over attempts, printed `name n (rate%)` — the
    decode-failure monitor surface (ISSUE 1: zero-canvas batches must be
    visible in the per-step meter line, not a discarded return value)."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0

    def update(self, count: int, total: int):
        self.count, self.total = int(count), int(total)

    @property
    def rate(self) -> float:
        return self.count / self.total if self.total else 0.0

    def __str__(self):
        return f"{self.name} {self.count} ({100.0 * self.rate:.2f}%)"


class Throughput:
    """imgs/sec (global and per-chip) over a rolling window."""

    def __init__(self, num_chips: int):
        self.num_chips = num_chips
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()
        self._images = 0

    def update(self, n_images: int):
        self._images += n_images

    @property
    def imgs_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._images / dt if dt > 0 else 0.0

    @property
    def imgs_per_sec_per_chip(self) -> float:
        return self.imgs_per_sec / max(self.num_chips, 1)
