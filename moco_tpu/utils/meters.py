"""Console metering (rebuild of `AverageMeter`/`ProgressMeter`,
`main_moco.py:≈L330-375`) plus the imgs/sec meter that IS the north-star
throughput metric (BASELINE.md derived-throughput row)."""

from __future__ import annotations

import time
from collections import deque


class AverageMeter:
    """Running value/average, printed as `name val (avg)`."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name, self.fmt = name, fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    def __str__(self):
        return ("{name} {val" + self.fmt + "} ({avg" + self.fmt + "})").format(
            name=self.name, val=self.val, avg=self.avg
        )


class ProgressMeter:
    def __init__(self, num_batches: int, meters, prefix: str = ""):
        fmt = "{:" + str(len(str(num_batches))) + "d}"
        self.batch_fmtstr = "[" + fmt + "/" + fmt.format(num_batches) + "]"
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int):
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        print("\t".join(entries), flush=True)


class RateMeter:
    """Cumulative event count over attempts, printed `name n (rate%)` — the
    decode-failure monitor surface (ISSUE 1: zero-canvas batches must be
    visible in the per-step meter line, not a discarded return value)."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0

    def update(self, count: int, total: int):
        self.count, self.total = int(count), int(total)

    @property
    def rate(self) -> float:
        return self.count / self.total if self.total else 0.0

    def __str__(self):
        return f"{self.name} {self.count} ({100.0 * self.rate:.2f}%)"


class Throughput:
    """imgs/sec, cumulative AND over a rolling window of recent updates.

    Cumulative (`imgs_per_sec`) is the honest epoch summary but is polluted
    for the whole epoch by the first-step compile stall; the rolling window
    (`rolling_imgs_per_sec`, last `window` updates) converges to the steady
    state within `window` steps, so the PER-STEP meter line reports it
    (ISSUE 2 satellite). `window=0` disables the rolling view (it then
    falls back to cumulative)."""

    def __init__(self, num_chips: int, window: int = 0):
        self.num_chips = num_chips
        self.window = max(int(window), 0)
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()
        self._images = 0
        # (timestamp, images-since-previous-entry); the reset sentinel
        # anchors the first interval, then slides out with the stall
        self._recent: deque | None = (
            deque([(self._t0, 0)], maxlen=self.window + 1) if self.window else None
        )

    def update(self, n_images: int):
        self._images += n_images
        if self._recent is not None:
            self._recent.append((time.perf_counter(), n_images))

    @property
    def imgs_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._images / dt if dt > 0 else 0.0

    @property
    def rolling_imgs_per_sec(self) -> float:
        """Rate over the last `window` updates (cumulative when disabled or
        before two entries exist). Entry 0 only anchors time: its images
        arrived before the window opened."""
        if self._recent is None or len(self._recent) < 2:
            return self.imgs_per_sec
        dt = self._recent[-1][0] - self._recent[0][0]
        images = sum(n for _, n in list(self._recent)[1:])
        return images / dt if dt > 0 else 0.0

    @property
    def imgs_per_sec_per_chip(self) -> float:
        return self.imgs_per_sec / max(self.num_chips, 1)
