"""Shared assembly + timing for the step-mode benchmark program.

One definition of "the benchmark" — the fused aug+train-step program built
the way the train driver builds it — used by `bench.py`'s step children,
`tools/_tpu_validate.py`, and `tools/_perf_ab.py`. Before r5 each of those
carried its own near-identical copy of this ~25-line block, which is
exactly how an A/B tool silently stops timing the same program the bench
publishes (review, r5). Every hyperparameter comes from the config; the
callers only choose WHICH config.

Timing semantics (measured on the sandbox's tunneled v5e, r2):
- `block_until_ready` does NOT reliably synchronize on the experimental
  axon PJRT relay — only a real device→host transfer does, so rounds sync
  with `float(loss)`.
- the first executions after compile are relay warmup (~seconds); steady
  state needs a generous warmup, then chained steps with one final sync
  amortize the ~70 ms relay round-trip.
- best-of-rounds dodges relay noise; a non-finite loss must never publish
  a number (asserted here, both at warmup and at the end).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def build_v2_fused_step(config, mesh, *, steps_per_epoch: int = 1000,
                        state_seed: int = 0, fused_seed: int = 1):
    """Assemble the fused aug+train-step program and its initial state for
    `config`, exactly as the train driver does (`config.variant` selects
    the v1/v2 queue step or the v3 queue-free step and the matching aug
    pair). Returns `(fused, state)`; `fused(state, imgs_u8, extents,
    step)` is the one jitted program."""
    from moco_tpu.data.augment import (
        aug_config_for,
        build_two_crops_sharded,
        with_dtype,
    )
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import (
        build_encoder,
        build_fused_step,
        build_optimizer,
        build_train_step,
    )

    n_chips = mesh.devices.size
    model = build_encoder(config)
    tx, sched = build_optimizer(config, steps_per_epoch=steps_per_epoch)
    local_shape = (config.batch_size // n_chips,
                   config.image_size, config.image_size, 3)
    if config.variant == "v3":
        from moco_tpu.v3_step import create_v3_train_state

        state = create_v3_train_state(
            jax.random.key(state_seed), model, tx, local_shape)
    else:
        state = create_train_state(
            jax.random.key(state_seed),
            model,
            tx,
            local_shape,
            config.num_negatives,
            config.embed_dim,
        )
    # gradient-sync accumulators (ISSUE 6), exactly as the driver attaches
    # them — a quantized/demo bench without the state would crash at trace
    from moco_tpu.parallel.gradsync import GradSync

    state = GradSync.for_mesh(config, mesh).attach(state, mesh)
    if getattr(config, "sharding", "dp") != "dp":
        # FSDP placement (ISSUE 15), exactly as the driver applies it —
        # the sharded bench must time the sharded program
        from moco_tpu.parallel import fsdp

        state = fsdp.place_state(state, mesh, config)
    step_fn = build_train_step(config, model, tx, mesh, steps_per_epoch,
                               sched, state=state)
    # the SAME variant->aug selection as the train driver (v1 presets get
    # the v1 recipe, not a silently-substituted v2 stack — review, r5)
    aug_cfg = with_dtype(aug_config_for(config), config.compute_dtype)
    two_crops = build_two_crops_sharded(aug_cfg, mesh)
    fused = build_fused_step(step_fn, two_crops, jax.random.key(fused_seed))
    return fused, state


def build_v2_fused_bench(config, mesh, *, steps_per_epoch: int = 1000,
                         state_seed: int = 0, fused_seed: int = 1,
                         data_seed: int = 0):
    """`build_v2_fused_step` plus one staged uint8 batch at the native
    staging shape (`image_size + image_size // 8`) — re-augmented on
    device every step, representing the steady-state input path with host
    decode amortized. Returns `(fused, state, imgs_u8, extents)`."""
    from moco_tpu.data.datasets import full_extents

    fused, state = build_v2_fused_step(
        config, mesh, steps_per_epoch=steps_per_epoch,
        state_seed=state_seed, fused_seed=fused_seed)
    stage = config.image_size + config.image_size // 8
    rng = np.random.RandomState(data_seed)
    imgs_u8 = jnp.asarray(
        rng.randint(0, 256, (config.batch_size, stage, stage, 3), dtype=np.uint8)
    )
    extents = full_extents(config.batch_size, stage, stage)
    return fused, state, imgs_u8, extents


def time_fused_step(fused, state, imgs_u8, extents, *, warmup: int,
                    steps: int, rounds: int = 2):
    """Warm up, then best-of-`rounds` timed runs of `steps` chained steps.

    Returns `(best_s_per_step, compile_warmup_s, final_loss, state)`.
    `compile_warmup_s` covers compile + relay warmup (the warmup loop,
    including its sync); with a warm persistent cache it collapses to
    relay warmup.
    """
    t_c = time.perf_counter()
    metrics = None
    for i in range(warmup):
        state, metrics = fused(state, imgs_u8, extents, i)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), f"non-finite warmup loss {loss}"
    compile_warmup_s = time.perf_counter() - t_c

    best = float("inf")
    for r in range(rounds):
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = fused(state, imgs_u8, extents, (r + 1) * 1000 + i)
        loss = float(metrics["loss"])
        best = min(best, (time.perf_counter() - t0) / steps)
    # a fast-but-wrong kernel must not publish a number
    assert np.isfinite(loss), f"non-finite benchmark loss {loss}"
    return best, compile_warmup_s, loss, state


def time_step_percentiles(fused, state, imgs_u8, extents, *, steps: int,
                          step_base: int = 10_000):
    """Per-step wall-time distribution: `steps` steps, EACH synced to the
    host via `float(loss)` (ISSUE 2: the tail — p95/p99 — is what a perf
    PR must not regress, and chained timing can only see the mean).

    The per-step sync adds one device→host round-trip to every sample
    (~70 ms on the tunneled relay, negligible on local backends), so these
    percentiles are comparable to EACH OTHER and to other synced runs —
    not to the chained `time_fused_step` mean. Returns
    `({"p50": ms, "p95": ms, "p99": ms}, state)`.
    """
    from moco_tpu.telemetry import percentiles_ms

    times = []
    for i in range(steps):
        t0 = time.perf_counter()
        state, metrics = fused(state, imgs_u8, extents, step_base + i)
        loss = float(metrics["loss"])  # the only reliable sync on the relay
        times.append(time.perf_counter() - t0)
    assert np.isfinite(loss), f"non-finite percentile-pass loss {loss}"
    return percentiles_ms(times), state
