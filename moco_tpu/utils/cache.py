"""Persistent XLA compilation cache (VERDICT r4 #2a).

The bench's TPU child must cold-compile the full fused R50 aug+step program
inside its budget window; on the tunneled relay that compile is the single
biggest unknown. With a persistent cache on disk, the FIRST healthy contact
pays the compile and every later run (the bench re-run, the horizon, the
validate tools) turns the same window into measurement time. The reference
has no analogue — CUDA kernels ship precompiled; XLA's compile-at-trace
model is what makes this cache load-bearing on TPU.

Call before building any jitted program. Opt out with MOCO_TPU_NO_CACHE=1
(tests leave it off via their own env; the cache dir is gitignored).
"""

from __future__ import annotations

import os
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".jax_cache")

# pid+ms alone can collide for two derivations in the same process tick
# (tests, a supervisor deriving twice); the sequence number cannot
_RUN_SEQ = 0


def per_run_cache_dir(base: str | None = None, tag: str = "run") -> str:
    """A compile-cache dir no OTHER process shares (ISSUE 5 satellite,
    applying the PR 4 finding): SIGKILL-grade death mid-write can poison
    this jax build's persistent cache — later loads of the poisoned entry
    heap-corrupt into a native-crash loop. Kill-risk workloads (supervised
    drills, a served process under an external orchestrator) therefore
    derive a fresh `<base>/per_run/<tag>-<pid>-<ms>` dir: poison dies with
    the run instead of infecting every later process on the host.

    Stdlib-only on purpose — tools/supervise.py (which never imports jax)
    sets this as the child's MOCO_TPU_CACHE_DIR. Old per-run dirs are just
    cache; delete them freely."""
    global _RUN_SEQ
    root = base or os.environ.get("MOCO_TPU_CACHE_ROOT") or DEFAULT_CACHE_DIR
    _RUN_SEQ += 1
    path = os.path.join(
        root, "per_run",
        f"{tag}-{os.getpid()}-{int(time.time() * 1e3)}-{_RUN_SEQ}",
    )
    os.makedirs(path, exist_ok=True)
    return path


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a repo-local dir.

    Returns the cache dir, or None when disabled (MOCO_TPU_NO_CACHE) or the
    running jax build lacks the flags (never fatal — the cache is an
    optimization, not a dependency)."""
    if os.environ.get("MOCO_TPU_NO_CACHE"):
        return None
    path = cache_dir or os.environ.get("MOCO_TPU_CACHE_DIR") or DEFAULT_CACHE_DIR
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        return None
    try:
        # cache everything that took real compile time; the default 1 GB
        # eviction policy keeps the dir bounded. Optional: a jax build
        # without this flag still has the cache ON via the dir above, so
        # the return value must say enabled either way
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (AttributeError, ValueError):
        # older jax: the threshold flag doesn't exist — the cache itself
        # stays enabled via the dir set above
        pass
    return path
