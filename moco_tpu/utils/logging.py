"""Scalar logging + profiling hooks (SURVEY §5.1/§5.5 build targets).

The reference has stdout meters only; the bl0 fork adds optional TensorBoard
scalars. Here: a thin tensorboardX writer (no-op when disabled or when the
package is missing) and a `jax.profiler` trace window — the traces open in
TensorBoard's profile plugin for MXU/HBM analysis."""

from __future__ import annotations


def log_event(kind: str, msg: str) -> None:
    """One-line structured event log (`[kind] msg`, flushed) — the channel
    the resilience subsystem reports through. A fixed `[kind]` prefix keeps
    preemption/rollback/chaos events greppable in multi-day run logs, where
    they would otherwise drown in the per-step meter lines."""
    print(f"[{kind}] {msg}", flush=True)


class ScalarWriter:
    """tensorboardX SummaryWriter wrapper; silently no-ops when `logdir` is
    empty or tensorboardX is unavailable."""

    def __init__(self, logdir: str = ""):
        self._writer = None
        if logdir:
            try:
                from tensorboardX import SummaryWriter

                self._writer = SummaryWriter(logdir)
            except ImportError:
                print(f"tensorboardX unavailable; not writing scalars to {logdir}")

    def write(self, step: int, scalars: dict) -> None:
        if self._writer is None:
            return
        for name, value in scalars.items():
            try:
                self._writer.add_scalar(name, float(value), step)
            except (TypeError, ValueError):
                continue

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


class ProfilerWindow:
    """Trace steps [start, stop) with jax.profiler into `logdir/plugins/...`
    (viewable with tensorboard-plugin-profile). Inactive when logdir == ""."""

    def __init__(self, logdir: str, start: int, stop: int):
        self.logdir, self.start, self.stop = logdir, start, stop
        self._active = False

    def maybe_toggle(self, step: int) -> None:
        if not self.logdir:
            return
        import jax

        if not self._active and self.start <= step < self.stop:
            # range check (not ==): a resumed run may start past `start`
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
