"""Scalar logging + profiling hooks (SURVEY §5.1/§5.5 build targets).

The reference has stdout meters only; the bl0 fork adds optional TensorBoard
scalars. Here: a thin tensorboardX writer (no-op when disabled or when the
package is missing), a `jax.profiler` trace window — the traces open in
TensorBoard's profile plugin for MXU/HBM analysis — and the structured
channels (ISSUE 2): `log_event` fans incidents out to registered sinks
(telemetry/ lands them in events.jsonl), and `info` is the ONE sanctioned
plain-line print, so tools/lint_robustness.py can forbid bare `print` in
the package and every event stays machine-consumable.
"""

from __future__ import annotations

# structured-event sinks (ISSUE 2): telemetry registers a callable
# `(kind, msg, fields) -> None` so resilience incidents land in the JSONL
# stream; the stdout line below stays — grepability in raw logs is a
# feature, not a fallback
_EVENT_SINKS: list = []


def add_event_sink(sink) -> None:
    if sink not in _EVENT_SINKS:
        _EVENT_SINKS.append(sink)


def remove_event_sink(sink) -> None:
    if sink in _EVENT_SINKS:
        _EVENT_SINKS.remove(sink)


def log_event(kind: str, msg: str, **fields) -> None:
    """One-line structured event log (`[kind] msg`, flushed) — the channel
    the resilience subsystem reports through. A fixed `[kind]` prefix keeps
    preemption/rollback/chaos events greppable in multi-day run logs, where
    they would otherwise drown in the per-step meter lines. Extra `fields`
    ride only the structured sinks (telemetry events.jsonl), not the line."""
    print(f"[{kind}] {msg}", flush=True)
    for sink in list(_EVENT_SINKS):
        try:
            sink(kind, msg, fields)
        except Exception as e:  # a broken sink must not take down the run
            print(f"[telemetry] event sink failed: {e!r}", flush=True)


def info(msg: str) -> None:
    """Plain human-facing line (flushed). The package's only sanctioned
    free-text print outside the meters: everything event-shaped must use
    `log_event` so it reaches the structured sinks."""
    print(msg, flush=True)


class ScalarWriter:
    """tensorboardX SummaryWriter wrapper; silently no-ops when `logdir` is
    empty or tensorboardX is unavailable (the unavailability warning prints
    on process 0 only — every host of a pod repeating it is noise).

    Unconvertible scalars are counted (`dropped`) and surfaced once per run
    through `log_event` instead of vanishing in a bare `continue`."""

    def __init__(self, logdir: str = ""):
        self._writer = None
        self.dropped = 0
        self._drop_warned = False
        if logdir:
            try:
                from tensorboardX import SummaryWriter

                self._writer = SummaryWriter(logdir)
            except ImportError:
                if _is_main_process():
                    info(f"tensorboardX unavailable; not writing scalars to {logdir}")

    def write(self, step: int, scalars: dict) -> None:
        if self._writer is None:
            return
        for name, value in scalars.items():
            try:
                self._writer.add_scalar(name, float(value), step)
            except (TypeError, ValueError):
                self.dropped += 1
                if not self._drop_warned:
                    self._drop_warned = True
                    log_event(
                        "scalar_writer",
                        f"dropped unconvertible scalar {name!r} "
                        f"({type(value).__name__}) at step {step}; further "
                        "drops are counted, see the run_end summary",
                        name=name, step=step,
                    )

    def flush(self) -> None:
        """Explicit flush, called alongside the telemetry flush cadence so
        TensorBoard curves and events.jsonl stay equally fresh mid-run."""
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


def _is_main_process() -> bool:
    """process_index 0, defaulting to True when jax has no backend yet (the
    writer must stay constructible before/without device init)."""
    try:
        import jax

        return jax.process_index() == 0
    except (ImportError, RuntimeError):
        return True


class ProfilerWindow:
    """Trace steps [start, stop) with jax.profiler into `logdir/plugins/...`
    (viewable with tensorboard-plugin-profile). Inactive when logdir == ""."""

    def __init__(self, logdir: str, start: int, stop: int):
        self.logdir, self.start, self.stop = logdir, start, stop
        self._active = False

    def maybe_toggle(self, step: int) -> None:
        if not self.logdir:
            return
        import jax

        if not self._active and self.start <= step < self.stop:
            # range check (not ==): a resumed run may start past `start`
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
