"""Version compatibility shims for the JAX API surface this repo uses.

The codebase targets the current public API (`jax.shard_map`,
`lax.axis_size`); older installed builds (0.4.x) ship the same
functionality under experimental/derived spellings. Routing every call
site through this module keeps the rest of the code written against ONE
(modern) API while still importing cleanly on the toolchain the
container actually has — the same stub-don't-install stance the repo
takes for optional deps.
"""

from __future__ import annotations

import jax
from jax import lax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # 0.4.x: public promotion landed later
    from jax.experimental.shard_map import shard_map  # type: ignore

# Modern jax defaults to partitionable threefry; 0.4.x defaulted to the
# original lowering, whose stream DIFFERS and is not invariant under
# sharding. The repo's recorded trajectories (tests/test_golden.py) and the
# sharded-equals-unsharded contracts assume the modern stream, so pin it.
if hasattr(jax.config, "jax_threefry_partitionable"):
    jax.config.update("jax_threefry_partitionable", True)


def axis_size(axis_name) -> jax.Array | int:
    """`lax.axis_size` (new API), or the psum-of-ones equivalent inside a
    mapped context on builds that predate it."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


import functools  # noqa: E402
import inspect  # noqa: E402

_SDS_HAS_VMA = "vma" in inspect.signature(jax.ShapeDtypeStruct.__init__).parameters


@functools.lru_cache(maxsize=1)
def _barrier_batchable() -> bool:
    import jax.numpy as jnp

    try:
        jax.vmap(lax.optimization_barrier)(jnp.zeros((2, 1)))
        return True
    except NotImplementedError:  # 0.4.x: no batching rule for the barrier
        return False


def optimization_barrier(x):
    """`lax.optimization_barrier`, or identity on builds whose barrier has no
    vmap batching rule (0.4.x). The barrier is a scheduling hint — dropping
    it changes compile determinism, never numerics — so identity is the
    correct degradation, and it is applied uniformly (also outside vmap) so
    a given build always compiles the same program."""
    if _barrier_batchable():
        return lax.optimization_barrier(x)
    return x


def shape_dtype_struct(shape, dtype, vma=frozenset()) -> jax.ShapeDtypeStruct:
    """`jax.ShapeDtypeStruct` with the varying-manual-axes annotation where
    the build supports it (pallas outputs inside shard_map must declare
    their vma explicitly on new jax). On 0.4.x the concept doesn't exist,
    so dropping the annotation is the correct lowering, not a loss."""
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
