"""Uniform environment-flag parsing for the Pallas-family switches.

`env_flag(name)` is True iff the variable is set to anything other than
"" or "0" — so "0" means OFF for every switch, including the DISABLE_*
spellings where =0 reads "not disabled". Before this helper the three
gates (fast_bn, fused_block, augment blur) each hand-rolled the check and
a truthy-string `os.environ.get` made MOCO_TPU_DISABLE_PALLAS=0 silently
kill every kernel family (review, r5).
"""

from __future__ import annotations

import os


def env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")
