"""Replicated serving control plane (ISSUE 10 tentpole).

PR 5 made one EmbedService process; one process is one SIGKILL away from
a dead endpoint. This module composes the two most battle-tested
subsystems in the repo — the PR 4 supervisor machinery and the PR 5
serve stack — into a production-shaped fleet:

  - `FleetSupervisor` spawns N `tools/serve.py` replicas on distinct
    ports and supervises them the PR 4 way: per-replica `/healthz`
    probes (a probe answer is the replica's heartbeat — staleness beyond
    the window gets the SIGTERM → grace → SIGKILL escalation, exactly
    the wedged-collective treatment), death classification through the
    shared exit-code protocol (`supervisor.classify_exit`), and a
    per-replica restart budget REFUNDED whenever the dead replica had
    reached healthy in its last life — a crash-looping replica exhausts
    its budget, a long-serving one restarts forever.
  - `FleetRouter` (stdlib `ThreadingHTTPServer`) load-balances
    `/v1/embed` and `/v1/knn` across in-rotation replicas by
    least-outstanding, ejects a replica on any connection-level failure
    (re-admission only through a later probe success), retries
    connection-refused/reset EXACTLY once on a different replica under
    the request's own deadline, and — when no healthy backend exists —
    sheds with a structured 503 + retry hint. Every request ends in an
    answer; the router never stalls and never silently drops.
  - rolling restarts are DRAIN-AWARE and never take capacity below N−1:
    one replica at a time, and only while every other active replica is
    healthy — drain (router stops picking it, SIGTERM lets serve.py
    finish in-flight work), relaunch, wait for the probe to readmit it,
    then move on.
  - `CheckpointWatcher` + the reload roll: a watch directory of exported
    encoder steps (`<dir>/<step>/...` with PR 1 integrity manifests) is
    polled; a step is deployed only once its manifest exists AND
    verifies — corrupt/partial steps are QUARANTINED with the PR 4
    preflight pattern (moved to `.quarantine/`, loudly, without crashing
    anything). A verified step rolls across the fleet via each replica's
    `POST /admin/reload`: the replica builds + warms the new engine
    off-path and swaps atomically between micro-batches, so a live
    pretrain run continuously deploys with zero dropped requests.
    Replicas that were down during a roll converge on relaunch (the new
    checkpoint is pinned into their argv) or on the next watcher pass.
  - versioned-bank lifecycle (ISSUE 16): with `bank_dir` set, a step
    deploys ONLY with a verifying paired bank built by
    tools/bank_build.py (`<bank_dir>/<step>/bank.npz` + an integrity
    manifest binding it to the checkpoint's content hash). The roll
    POSTs the pair and each replica dual-swaps (engine, bank) under one
    generation bump; a `reload_bank_mismatch` verdict (the replica's
    space-agreement probe) quarantines the PAIR as a unit, restores the
    pre-roll last-known-good pair, and rolls back half-swapped
    replicas. A manifest-less bank just WAITS (`bank_waiting` event) —
    a bank-free fleet (empty bank_dir) is byte-for-byte unaffected.

Every lifecycle transition lands as a `kind: "fleet"` record in the
fleet's events.jsonl, stamped with the PR 8 run/trace ids the replicas
inherit through their env — one merged story across router, supervisor
and N serving processes.

Pure stdlib by contract (mocolint R11 fleet-stdlib-only, transitive
through moco_tpu modules): the routing tier must stay alive and tiny
while replicas OOM, segfault, or poison their compile caches.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import math
import os
import random
import socket
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from moco_tpu.resilience.integrity import manifest_path, verify_step
from moco_tpu.resilience.supervisor import (
    CLASS_CLEAN,
    FATAL_CLASSES,
    QUARANTINE_DIRNAME,
    classify_exit,
)
from moco_tpu.telemetry.aggregate import PercentileWindow
from moco_tpu.telemetry.trace import Tracer
from moco_tpu.utils.logging import log_event

EVENTS_FILENAME = "events.jsonl"

# payload suffixes the reload roll recognizes inside a watched step dir
_EXPORT_SUFFIXES = (".safetensors", ".npz", ".bin")

# structured error codes the router itself originates (the replica-side
# codes — overloaded/deadline_exceeded/draining — pass through untouched)
SHED_NO_BACKEND = "no_healthy_backend"
SHED_UPSTREAM_TIMEOUT = "upstream_timeout"
SHED_UPSTREAM_ERROR = "upstream_error"
SHED_DEADLINE_ROUTER = "deadline_exceeded"  # budget elapsed AT the router
                                            # (same code the replica uses —
                                            # a client retries either the
                                            # same way — but counted in its
                                            # own router_stats bucket)


class FleetLaunchError(RuntimeError):
    """A replica COMMAND could not be spawned at fleet start (missing
    binary, exec failure). Distinct from the router's bind OSError on
    purpose: the CLI maps the bind to EXIT_FLEET_BIND=48 (reschedule —
    don't race the socket) and this to EXIT_CONFIG_ERROR=45 (the same
    argv can never succeed)."""


def pick_free_port(host: str = "127.0.0.1") -> int:
    """Ephemeral-port discovery for auto replica ports (tests, bench).
    Races are possible between close and the child's bind; a loser exits
    EXIT_SERVE_BIND and the fleet classifies it fatal — loud, not flaky."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class FleetPolicy:
    """Fleet supervision knobs (tools/serve_fleet.py exposes each)."""

    probe_secs: float = 1.0            # per-replica /healthz cadence
    probe_timeout_s: float = 2.0       # one probe's connect+answer budget
    health_stale_secs: float = 10.0    # no probe ANSWER for this long
                                       # (once healthy this life) -> the
                                       # replica is wedged: kill it
    startup_grace_secs: float = 300.0  # launch -> first healthy probe
                                       # allowance (jax import + ladder
                                       # compile on a cold replica)
    term_grace_secs: float = 15.0      # SIGTERM -> grace -> SIGKILL
    max_restarts: int = 5              # consecutive never-healthy deaths
                                       # per replica before abandoning it;
                                       # a healthy life refunds in full
    backoff_base_secs: float = 0.5
    backoff_max_secs: float = 30.0
    backoff_jitter: float = 0.2
    request_timeout_s: float = 30.0    # router default per-request
                                       # deadline (body deadline_ms wins)
    watch_poll_secs: float = 1.0       # checkpoint-watcher cadence
    reload_timeout_s: float = 300.0    # one replica's /admin/reload budget
                                       # (checkpoint load + full ladder
                                       # warmup, off the request path)
    stats_every_secs: float = 30.0     # router_stats event cadence (the
                                       # autoscaler input stream; see
                                       # _emit_router_stats for the schema)
    stats_latency_window: int = 512    # router-latency ring size behind
                                       # the router_stats p50/p95/p99
    # telemetry-driven autoscaling (ISSUE 20): the controller consumes
    # the SAME windowed router_stats stream obsd reads — counter deltas
    # between consecutive emits — with hysteresis (consecutive breached/
    # idle windows) and a cooldown so one noisy window never flaps the
    # fleet. autoscale_max=0 (default) disables the whole subsystem.
    autoscale_min: int = 1             # never reap below this many
                                       # (shard cover raises the floor)
    autoscale_max: int = 0             # replica budget; 0 = autoscaler off
    autoscale_cooldown_s: float = 60.0 # min gap between scale actions
    autoscale_up_after: int = 2        # consecutive breached windows
    autoscale_down_after: int = 6      # consecutive idle windows
    autoscale_shed_high: float = 0.02  # shed-rate breach threshold
    autoscale_outstanding_high: float = 4.0  # in-flight per healthy
                                       # replica breach threshold
    autoscale_p99_high_ms: float = 0.0 # p99 breach threshold; 0 = off
    autoscale_idle_low: float = 0.25   # outstanding/healthy below this
                                       # (and zero sheds) counts as idle

    def backoff_secs(self, consecutive_failures: int,
                     rng: random.Random) -> float:
        base = min(
            self.backoff_base_secs
            * (2.0 ** max(consecutive_failures - 1, 0)),
            self.backoff_max_secs,
        )
        return base * (1.0 + self.backoff_jitter * rng.random())


class ReplicaState:
    """One replica's supervision state. Every mutable field is guarded by
    the fleet's lock; the router reads/writes `outstanding` under it."""

    def __init__(self, index: int, host: str, port: int,
                 telemetry_dir: str, budget: int):
        self.index = index
        self.host = host
        self.port = port
        self.telemetry_dir = telemetry_dir
        self.proc: subprocess.Popen | None = None
        self.pid: int | None = None
        self.launches = 0
        self.budget = budget
        self.consecutive_failures = 0
        self.healthy = False           # last probe answered 200 (rotation)
        self.draining = False          # roll/stop took it out on purpose
        self.abandoned = False         # fatal class or exhausted budget
        self.expected_exit = False     # WE asked it to exit (roll, stop)
        self.outstanding = 0           # router's in-flight count
        self.shard: int | None = None  # owned ANN cell partition (ISSUE
                                       # 20); None on ann-free fleets
        self.reaping = False           # autoscale drain-then-reap in
                                       # progress: never relaunched,
                                       # removed from the table once the
                                       # process is gone
        self.launched_at = 0.0
        self.last_ok_life: float | None = None  # newest probe ANSWER (200
                                       # or draining-503) this life
        self.ever_healthy_life = False
        self.kill_phase: str | None = None      # None | "term" | "kill"
        self.term_at = 0.0
        self.relaunch_at: float | None = None   # pending relaunch time
        self.deployed_step = -1        # newest hot-reloaded step
        self.reload_announced = -1     # dedupe for reload_failed events
        self.reload_refused_step = -1  # replica answered 409 for this
                                       # step: a TERMINAL refusal (kNN
                                       # bank, ladder change) — re-trying
                                       # every pass would make the
                                       # replica load+warm a checkpoint
                                       # just to refuse it again; cleared
                                       # on relaunch (fresh argv pins the
                                       # payload)
        self.classifications: list[str] = []

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def snapshot(self) -> dict:
        return {
            "replica": self.index,
            "port": self.port,
            "pid": self.pid,
            "healthy": self.healthy,
            "draining": self.draining,
            "abandoned": self.abandoned,
            "outstanding": self.outstanding,
            "shard": self.shard,
            "launches": self.launches,
            "restarts": max(self.launches - 1, 0),
            "budget_left": self.budget,
            "deployed_step": self.deployed_step,
            "classifications": list(self.classifications),
        }


# ---------------------------------------------------------------------------
# the front-end router
# ---------------------------------------------------------------------------


class _RouterServer(ThreadingHTTPServer):
    daemon_threads = True
    # same reasoning as serve/http.py: a backlog of 5 resets reconnecting
    # closed-loop clients; the structured shed is the admission control
    request_queue_size = 128


def _make_router_handler(fleet: "FleetSupervisor"):
    policy = fleet.policy

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass  # per-request stderr drowns the structured channel

        def _send(self, status: int, obj: dict) -> None:
            body = json.dumps(obj).encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_raw(self, status: int, body: bytes) -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                healthy = fleet.healthy_count()
                body = {
                    "status": "ok" if healthy else "no_healthy_backend",
                    "healthy": healthy,
                    "replicas": len(fleet.replicas),
                }
                self._send(200 if healthy else 503, body)
            elif self.path == "/stats":
                self._send(200, fleet.stats())
            else:
                self._send(404, {"error": "not_found", "path": self.path})

        def do_POST(self):
            # /admin/* is deliberately NOT proxied: reload/ops surface
            # stays on the replicas' own ports, reachable only by the
            # fleet supervisor (or an operator), never by public traffic
            if self.path not in ("/v1/embed", "/v1/knn"):
                self.rfile.read(int(self.headers.get("Content-Length") or 0))
                self._send(404, {"error": "not_found", "path": self.path})
                return
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length)
            status, out = fleet.router_proxy(self.path, body)
            self._send_raw(status, out)

    return Handler


class FleetRouter:
    """Owns the front-end `ThreadingHTTPServer`; the routing logic itself
    lives on the fleet (it needs the replica table). `port=0` binds an
    ephemeral port exposed as `.port`."""

    def __init__(self, fleet: "FleetSupervisor", host: str, port: int):
        self.server = _RouterServer((host, port),
                                    _make_router_handler(fleet))
        self.host, self.port = self.server.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True,
            name="fleet-router",
        )
        self._thread.start()

    def shutdown(self) -> None:
        if self._thread is not None:
            # BaseServer.shutdown() BLOCKS until serve_forever acks —
            # calling it on a bound-but-never-started server (the
            # partial-start cleanup path) would hang forever
            self.server.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self.server.server_close()


# ---------------------------------------------------------------------------
# checkpoint watcher (unit-testable standalone; the fleet runs it in a
# thread and rolls what it finds)
# ---------------------------------------------------------------------------


class CheckpointWatcher:
    """Poll a directory of exported encoder steps (`<dir>/<step>/<file>`
    + `.integrity/<step>.json` manifests, the PR 1 layout) for new
    deployable checkpoints.

    Deployment gate, in order: a step WITHOUT a manifest is skipped
    silently (the exporter writes the manifest last, atomically — its
    absence means the step is still being written); a step whose
    manifest FAILS verification is quarantined to `.quarantine/<step>`
    with the PR 4 preflight pattern and never considered again; the
    NEWEST verifying step wins (older not-yet-deployed steps are
    skipped — serving wants the freshest weights, not a replay).
    `poll_once()` returns `(step, payload_path)` for a newly deployable
    step, else None."""

    def __init__(self, watch_dir: str, *, floor: int = -1, emit=None):
        self.watch_dir = watch_dir
        self.floor = floor          # newest step already seen/deployed
        self._emit = emit or (lambda event, **fields: None)
        self._bad_layout: set[int] = set()

    def poll_once(self) -> tuple[int, str] | None:
        try:
            names = os.listdir(self.watch_dir)
        except OSError:
            return None  # watch dir not created yet
        steps = sorted((int(n) for n in names if n.isdigit()), reverse=True)
        for step in steps:
            if step <= self.floor:
                break  # newest-first: everything older is already decided
            if step in self._bad_layout:
                continue
            if not os.path.exists(manifest_path(self.watch_dir, step)):
                continue  # still being exported: manifest lands last
            reason = verify_step(self.watch_dir, step)
            if reason is not None:
                self._quarantine(step, reason)
                continue
            payload = self._payload(step)
            if payload is None:
                self._bad_layout.add(step)
                self._emit("reload_bad_layout", step=step,
                           detail="no single export payload in step dir")
                continue
            self.floor = step
            return step, payload
        return None

    def _payload(self, step: int) -> str | None:
        step_dir = os.path.join(self.watch_dir, str(step))
        try:
            files = sorted(
                f for f in os.listdir(step_dir)
                if os.path.isfile(os.path.join(step_dir, f))
            )
        except OSError:
            return None
        known = [f for f in files if f.endswith(_EXPORT_SUFFIXES)]
        chosen = known[0] if known else (files[0] if len(files) == 1 else None)
        return os.path.join(step_dir, chosen) if chosen else None

    def quarantine(self, step: int, reason: str) -> None:
        """Quarantine a step AFTER discovery (the reload drift guard's
        path, ISSUE 13: the manifest verified — the bytes are intact —
        but the WEIGHTS are degenerate). Filesystem errors are emitted,
        not raised: the caller is the fleet's reload roll, which must
        keep rolling whatever the watch dir allows."""
        try:
            self._quarantine(step, reason)
        except OSError as e:
            self._emit("reload_watch_error",
                       detail=f"quarantine step {step}: "
                              f"{type(e).__name__}: {e}")

    def _quarantine(self, step: int, reason: str) -> None:
        qdir = os.path.join(self.watch_dir, QUARANTINE_DIRNAME)
        os.makedirs(qdir, exist_ok=True)
        target = os.path.join(qdir, str(step))
        if os.path.exists(target):
            target = f"{target}.{int(time.time())}"
        os.rename(os.path.join(self.watch_dir, str(step)), target)
        try:
            os.remove(manifest_path(self.watch_dir, step))
        except OSError:
            pass
        self._emit("reload_quarantine", step=step, reason=reason,
                   moved_to=target)
        log_event(
            "fleet",
            f"quarantined corrupt checkpoint step {step} ({reason}) "
            f"-> {target}; the fleet keeps serving the previous weights",
        )

    def run(self, poll_secs: float, stop: threading.Event, on_new) -> None:
        """Thread body: poll until `stop`; `on_new(step, path)` for each
        newly deployable step (the fleet's reload roll). A filesystem
        error mid-poll (unwritable quarantine dir, a file vanishing
        between stat and hash) must not kill the watcher thread — that
        would silently disable hot reload for the fleet's lifetime
        while everything reports healthy. Errors are emitted and the
        next poll retries."""
        while not stop.is_set():
            try:
                found = self.poll_once()
                if found is not None:
                    on_new(*found)
            except OSError as e:
                self._emit("reload_watch_error",
                           detail=f"{type(e).__name__}: {e}")
                log_event("fleet",
                          f"checkpoint watcher error (will retry): {e}")
            stop.wait(poll_secs)


# ---------------------------------------------------------------------------
# telemetry-driven autoscaling (ISSUE 20)
# ---------------------------------------------------------------------------


class AutoscaleController:
    """Pure decision logic for telemetry-driven autoscaling.

    Feed it consecutive router_stats-shaped snapshots (cumulative
    counters + gauges) on the stats cadence; it answers ("up"|"down",
    reason) or None. Deliberately free of threads, wall clocks, and
    fleet state so the hysteresis is unit-testable with plain dicts:

      - breach: windowed shed RATE (Δsheds/Δrequests) above
        `autoscale_shed_high`, in-flight depth per healthy replica
        above `autoscale_outstanding_high`, or — when enabled — p99
        above `autoscale_p99_high_ms`;
      - idle: ZERO sheds this window AND depth per healthy replica
        below `autoscale_idle_low`;
      - hysteresis: `autoscale_up_after` consecutive breached windows
        scale up, `autoscale_down_after` consecutive idle ones scale
        down; a mixed window resets both streaks — one noisy sample
        never moves capacity;
      - cooldown: actions at least `autoscale_cooldown_s` apart.
        Streaks KEEP accumulating through a cooldown, so a sustained
        breach fires the moment the window reopens.
    """

    SHED_KEYS = ("shed_no_backend", "upstream_timeout", "upstream_error",
                 "shed_deadline_router")

    def __init__(self, policy: FleetPolicy):
        self.policy = policy
        self._prev: dict | None = None
        self.breach_streak = 0
        self.idle_streak = 0
        self.last_action_at = float("-inf")

    def observe(self, stats: dict, now: float) -> tuple[str, str] | None:
        p = self.policy
        prev, self._prev = self._prev, dict(stats)
        if prev is None:
            return None  # first window: no deltas yet
        d_req = stats.get("requests", 0) - prev.get("requests", 0)
        d_shed = sum(stats.get(k, 0) - prev.get(k, 0)
                     for k in self.SHED_KEYS)
        shed_rate = d_shed / max(d_req, 1)
        healthy = max(int(stats.get("healthy") or 0), 1)
        depth = float(stats.get("outstanding") or 0) / healthy
        p99 = float((stats.get("latency_ms") or {}).get("p99") or 0.0)
        breach = None
        if shed_rate > p.autoscale_shed_high:
            breach = (f"shed_rate {shed_rate:.4f} > "
                      f"{p.autoscale_shed_high} over the window")
        elif depth > p.autoscale_outstanding_high:
            breach = (f"outstanding/healthy {depth:.2f} > "
                      f"{p.autoscale_outstanding_high}")
        elif p.autoscale_p99_high_ms and p99 > p.autoscale_p99_high_ms:
            breach = f"p99 {p99:.1f}ms > {p.autoscale_p99_high_ms}ms"
        if breach is not None:
            self.breach_streak += 1
            self.idle_streak = 0
        elif d_shed == 0 and depth < p.autoscale_idle_low:
            self.idle_streak += 1
            self.breach_streak = 0
        else:
            self.breach_streak = 0
            self.idle_streak = 0
        if now - self.last_action_at < p.autoscale_cooldown_s:
            return None
        if self.breach_streak >= p.autoscale_up_after:
            self.breach_streak = 0
            self.last_action_at = now
            return "up", breach
        if self.idle_streak >= p.autoscale_down_after:
            self.idle_streak = 0
            self.last_action_at = now
            return "down", (f"idle for {p.autoscale_down_after} windows "
                            f"(outstanding/healthy {depth:.2f} < "
                            f"{p.autoscale_idle_low}, zero sheds)")
        return None


# ---------------------------------------------------------------------------
# the fleet supervisor
# ---------------------------------------------------------------------------


class FleetSupervisor:
    """Supervise N serve replicas behind one router.

    `child_argv(index, port, telemetry_dir, pretrained[, bank])` builds
    one replica's command (tools/serve_fleet.py appends `--port`/
    `--telemetry-dir` — and, after a hot reload, `--pretrained` and,
    for dual-swap fleets, `--knn-bank` — to the operator's base
    command; tests point it at stub scripts; a 4-arg callable still
    works for bank-free fleets). `pretrained` is None until a watcher
    deployment happens, then the deployed payload path — a replica
    relaunched after a reload roll must come back with the NEW weights
    (and bank), not the boot-time ones."""

    def __init__(
        self,
        child_argv,
        *,
        replicas: int,
        telemetry_dir: str,
        host: str = "127.0.0.1",
        router_port: int = 0,
        base_port: int = 0,
        policy: FleetPolicy | None = None,
        watch_dir: str = "",
        bank_dir: str = "",
        env: dict | None = None,
        replica_env: dict | None = None,
        seed: int | None = None,
        ann_shards: int = 0,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if ann_shards < 0:
            raise ValueError(f"ann_shards must be >= 0, got {ann_shards}")
        if ann_shards and replicas < ann_shards:
            raise ValueError(
                f"ann_shards={ann_shards} needs at least that many "
                f"replicas to cover every cell partition, got "
                f"{replicas}"
            )
        self._child_argv = child_argv
        self.n_replicas = int(replicas)
        self.ann_shards = int(ann_shards)
        self.telemetry_dir = telemetry_dir
        self.host = host
        self._router_port = router_port
        self._base_port = base_port
        self.policy = policy or FleetPolicy()
        if self.policy.autoscale_max:
            if self.policy.autoscale_min < 1:
                raise ValueError("autoscale_min must be >= 1")
            if self.policy.autoscale_max < max(self.policy.autoscale_min,
                                               replicas):
                raise ValueError(
                    f"autoscale_max={self.policy.autoscale_max} below "
                    f"max(autoscale_min={self.policy.autoscale_min}, "
                    f"replicas={replicas})"
                )
        self.watch_dir = watch_dir
        self._env = env
        self._replica_env = dict(replica_env or {})
        self._rng = random.Random(seed)  # None -> system entropy (PR 4
                                         # lesson: no fleet-wide lockstep)
        self.events_path = os.path.join(telemetry_dir, EVENTS_FILENAME)
        self.incidents: list[dict] = []
        # ONE run id for router + supervisor + every replica (PR 8):
        # replicas inherit it via env, so their serve snapshots and the
        # fleet's lifecycle records merge into one timeline
        self.tracer = Tracer(telemetry_dir, "steps", proc="fleet")
        self.run_id = self.tracer.run_id
        self._lock = threading.Lock()
        self._emit_lock = threading.Lock()
        self.replicas: list[ReplicaState] = []
        self.router: FleetRouter | None = None
        self.failed = False            # every replica abandoned
        self._stop = threading.Event()
        self._monitor_thread: threading.Thread | None = None
        self._watch_thread: threading.Thread | None = None
        self._watcher: CheckpointWatcher | None = None
        self._roll: dict | None = None
        self._roll_requested = False
        self._target_step = -1
        self._target_path: str | None = None
        self._announced_step = -1
        self._good_pretrained: str | None = None  # last payload every
                                       # replica deployed (quarantine
                                       # rollback target, ISSUE 13)
        # versioned-bank lifecycle (ISSUE 16): when bank_dir is set, a
        # checkpoint step deploys ONLY with a verifying paired bank
        # (`<bank_dir>/<step>/bank.npz` + `.integrity/<step>.json`) —
        # the dual swap rolls (engine, bank) together; a mismatched
        # pair is quarantined as a UNIT and half-swapped replicas roll
        # back to the last-known-good pair below. Empty bank_dir =
        # bank-free fleet: zero behavior change.
        self.bank_dir = bank_dir
        self._good_bank: str | None = None
        self._good_step = -1
        self._prev_good: tuple | None = None  # (pretrained, bank, step)
                                       # BEFORE the in-flight roll: a
                                       # mismatch caught on a LATER
                                       # replica must not call the bad
                                       # pair "last known good"
        self._bank_verified: set[int] = set()
        self._bad_banks: set[int] = set()
        self._bank_waiting_step = -1   # dedupe for bank_waiting emits
        # the roll runs from the watcher thread (new step) AND the
        # monitor thread (a recovered replica converging): serialize so
        # one replica never sees two concurrent /admin/reload POSTs
        self._reload_roll_lock = threading.Lock()
        self._current_pretrained: str | None = None
        self._last_shed_event = float("-inf")
        self._last_stats_event = 0.0
        # router counters (guarded by _lock)
        self.r_requests = 0
        self.r_ok = 0
        self.r_retries = 0
        self.r_retry_ok = 0
        self.r_shed_no_backend = 0
        self.r_upstream_timeout = 0
        self.r_upstream_error = 0
        self.r_deadline_router = 0     # budget elapsed AT the router before
                                       # an attempt could even be forwarded
        self.r_passthrough_error = 0   # replica answered non-200 (its own
                                       # structured shed: counted, passed)
        # tiered admission + sharded-kNN counters (ISSUE 20)
        self.r_tier = {"interactive": 0, "batch": 0}
        self.r_knn_fanout = 0          # /v1/knn requests scatter-gathered
                                       # across ANN shards
        self.r_knn_partial = 0         # fan-outs answered with < every
                                       # shard (flagged partial: true)
        # answered-request latency window (lock-free GIL-atomic appends
        # from handler threads) behind router_stats' p50/p95/p99
        self._router_latency = PercentileWindow(
            self.policy.stats_latency_window)
        # end-to-end fan-out latency (embed leg + scatter + merge) — a
        # separate window so the merge overhead stays observable next to
        # the single-backend p99
        self._knn_merge = PercentileWindow(self.policy.stats_latency_window)
        # autoscaling (ISSUE 20): replica indices keep growing past the
        # boot count so a reaped index is never reused (telemetry dirs
        # and event streams stay unambiguous)
        self._next_index = self.n_replicas
        self._autoscaler = AutoscaleController(self.policy)

    # -- structured events ---------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        self._emit_record("fleet", event, **fields)

    def _emit_record(self, kind: str, event: str, **fields) -> None:
        """One structured record into events.jsonl + incidents. Fleet
        lifecycle stays `kind:"fleet"`; the bank lifecycle (ISSUE 16)
        emits `kind:"bank"` under the SAME run_id so a promotion's
        build/swap/quarantine/rollback and the fleet's reload roll are
        one timeline for obsd and telemetry_report."""
        record = {"v": 1, "t": round(time.time(), 3), "kind": kind,
                  "event": event, "run_id": self.run_id,
                  "trace_id": self.tracer.trace_id}
        record.update(fields)
        os.makedirs(self.telemetry_dir, exist_ok=True)
        with self._emit_lock:
            self.incidents.append(record)
            with open(self.events_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
                f.flush()
                os.fsync(f.fileno())
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        log_event(kind, f"{event} {detail}".strip())

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Bind the router (its OSError propagates raw — the CLI maps it
        to EXIT_FLEET_BIND), launch every replica, start the monitor
        (and the checkpoint watcher when configured). Every OTHER
        OSError (unwritable telemetry dir, un-spawnable replica command)
        is re-raised as FleetLaunchError: 48 means 'reschedule me' and a
        filesystem/argv problem rescheduled is an infinite loop."""
        try:
            os.makedirs(self.telemetry_dir, exist_ok=True)
        except OSError as e:
            raise FleetLaunchError(
                f"cannot create telemetry dir {self.telemetry_dir!r}: {e}"
            ) from e
        self.router = FleetRouter(self, self.host, self._router_port)
        try:
            ports = []
            for i in range(self.n_replicas):
                port = (self._base_port + i if self._base_port
                        else pick_free_port(self.host))
                ports.append(port)
                rdir = os.path.join(self.telemetry_dir, f"replica{i}")
                os.makedirs(rdir, exist_ok=True)
                r = ReplicaState(i, self.host, port, rdir,
                                 self.policy.max_restarts)
                if self.ann_shards:
                    # round-robin cell-partition ownership: replicas
                    # i, i+shards, ... serve shard i%shards, so every
                    # shard keeps cover while any ⌈N/shards⌉ subset of
                    # its owners is healthy
                    r.shard = i % self.ann_shards
                self.replicas.append(r)
            self._emit("fleet_start", replicas=self.n_replicas,
                       ports=ports, router=self.router.url,
                       ann_shards=self.ann_shards or None,
                       watch_dir=self.watch_dir or None)
            for r in self.replicas:
                self._launch(r)
        except OSError as e:
            # a replica COMMAND that cannot spawn (FileNotFoundError,
            # EMFILE...) or a replica dir/log that cannot be written:
            # kill whatever did launch and release the router — a
            # partial start must not leak processes — and re-raise as a
            # non-OSError so the CLI can't mistake it for a bind failure
            for r in self.replicas:
                if r.alive():
                    r.proc.kill()
                    r.proc.wait()
            self.router.shutdown()
            raise FleetLaunchError(
                f"cannot start the fleet: {e}"
            ) from e
        self.router.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True, name="fleet-monitor"
        )
        self._monitor_thread.start()
        if self.watch_dir:
            self._watcher = CheckpointWatcher(self.watch_dir,
                                              emit=self._emit)
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True, name="fleet-watcher"
            )
            self._watch_thread.start()

    def stop(self, timeout_s: float = 30.0) -> None:
        """Drain-stop: SIGTERM every replica (serve.py finishes accepted
        work), wait, escalate stragglers, then stop the router."""
        with self._lock:
            already = self._stop.is_set()
        if already:
            return
        self._emit("fleet_stop_begin", healthy=self.healthy_count())
        self._stop.set()
        for t in (self._monitor_thread, self._watch_thread):
            if t is not None:
                t.join(timeout=max(self.policy.probe_timeout_s * 2, 5.0))
        for r in self.replicas:
            with self._lock:
                r.draining = True
                r.expected_exit = True
            if r.alive():
                r.proc.terminate()
        deadline = time.monotonic() + timeout_s
        for r in self.replicas:
            if r.proc is None:
                continue
            while r.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if r.proc.poll() is None:
                r.proc.kill()
                r.proc.wait()
        self._emit_router_stats(final=True)
        self._emit("fleet_stop",
                   launches=sum(r.launches for r in self.replicas))
        if self.router is not None:
            self.router.shutdown()
        self.tracer.close()

    def healthy_count(self) -> int:
        with self._lock:
            return sum(
                1 for r in self.replicas
                if r.healthy and not r.draining and not r.abandoned
            )

    def stats(self) -> dict:
        with self._lock:
            out = {
                "run_id": self.run_id,
                "router": self._router_counters(),
                "replicas": [r.snapshot() for r in self.replicas],
                "target_step": self._target_step,
                "rolling_restart": self._roll is not None,
            }
            if self.bank_dir:
                out["bank"] = {
                    "dir": self.bank_dir,
                    "good_step": self._good_step,
                    "good_bank": self._good_bank,
                    "quarantined": sorted(self._bad_banks),
                }
            return out

    def _router_counters(self) -> dict:
        # caller holds the lock
        return {
            "requests": self.r_requests,
            "ok": self.r_ok,
            "retries": self.r_retries,
            "retry_ok": self.r_retry_ok,
            "shed_no_backend": self.r_shed_no_backend,
            "upstream_timeout": self.r_upstream_timeout,
            "upstream_error": self.r_upstream_error,
            "shed_deadline_router": self.r_deadline_router,
            "passthrough_non_200": self.r_passthrough_error,
            "requests_interactive": self.r_tier["interactive"],
            "requests_batch": self.r_tier["batch"],
            "knn_fanout": self.r_knn_fanout,
            "knn_partial": self.r_knn_partial,
        }

    # -- routing (called from router handler threads) ------------------------
    def pick_backend(self, exclude=()) -> ReplicaState | None:
        with self._lock:
            cands = [
                r for r in self.replicas
                if r.healthy and not r.draining and not r.abandoned
                and r.proc is not None and r.index not in exclude
            ]
            if not cands:
                return None
            r = min(cands, key=lambda c: (c.outstanding, c.index))
            r.outstanding += 1
            return r

    def release_backend(self, r: ReplicaState) -> None:
        with self._lock:
            r.outstanding = max(r.outstanding - 1, 0)

    def eject(self, r: ReplicaState, reason: str) -> None:
        """Take a replica out of rotation NOW (router-observed failure or
        probe failure). Re-admission only through a later probe success —
        one bad connect must not flap it back in by itself."""
        with self._lock:
            was = r.healthy
            r.healthy = False
        if was:
            self._emit("eject", replica=r.index, reason=reason)

    def router_proxy(self, path: str, body: bytes) -> tuple[int, bytes]:
        """One client request: count its admission tier, then either
        scatter-gather `/v1/knn` across the ANN shards (ISSUE 20) or
        route it to one backend. Returns (status, response bytes)."""
        with self._lock:
            self.r_requests += 1
            self.r_tier[self._tier_of(body)] += 1
        if path == "/v1/knn" and self.ann_shards > 1:
            return self._knn_fanout(body)
        return self._routed_request(path, body)

    def _tier_of(self, body: bytes) -> str:
        """The request's admission tier for the router's per-tier
        counters (the replica's MicroBatcher enforces the lanes; the
        router only accounts). Same substring pre-check as _deadline_s:
        the common untagged path never pays a JSON parse."""
        if b'"tier"' in body:
            try:
                if json.loads(body).get("tier") == "batch":
                    return "batch"
            except (ValueError, json.JSONDecodeError):
                pass  # malformed body: the replica answers 400 either way
        return "interactive"

    def _routed_request(self, path: str, body: bytes,
                        leg: bool = False) -> tuple[int, bytes]:
        """Pick → forward → (maybe) retry once on a DIFFERENT replica →
        answer. `leg=True` (a fan-out's embed phase) suppresses the
        success-path ok/latency accounting — the fan-out counts its own
        end-to-end outcome — while every shed/timeout path still counts
        and observes: those ARE the client's final answer."""
        t_start = time.monotonic()
        deadline = t_start + self._deadline_s(body)
        tried: list[int] = []
        last_err = "?"
        for attempt in (0, 1):
            replica = self.pick_backend(exclude=tried)
            if replica is None:
                if tried:
                    # the client DID wait through a failed attempt before
                    # this shed — that time belongs in the window (a
                    # zero-wait first-attempt shed does not: thousands of
                    # instant 503s during an outage would bury the tail)
                    self._router_latency.observe(
                        time.monotonic() - t_start)
                return self._shed_no_backend()
            tried.append(replica.index)
            remaining = deadline - time.monotonic()
            if remaining <= 0.01:
                # the picked replica never saw the request: hand its
                # outstanding slot back (leaking it here would skew
                # least-outstanding AND the autoscaler's depth gauge
                # upward forever), and the elapsed time — the client DID
                # wait the whole deadline — belongs in the latency
                # window like the upstream-timeout case
                self.release_backend(replica)
                self._router_latency.observe(time.monotonic() - t_start)
                with self._lock:
                    self.r_deadline_router += 1
                return 504, json.dumps({
                    "error": SHED_DEADLINE_ROUTER,
                    "detail": "request deadline elapsed at the router",
                }).encode()
            try:
                status, data = self._forward(replica, path, body, remaining)
            except (ConnectionError, http.client.HTTPException) as e:
                # the replica died under us (refused / reset / torn
                # response). Embeddings are pure functions of the input,
                # so a replay is safe — retry ONCE on a different replica
                self.eject(replica, f"connect:{type(e).__name__}")
                last_err = f"{type(e).__name__}: {e}"
                with self._lock:
                    self.r_retries += 1
                continue
            except (TimeoutError, OSError) as e:
                # a timeout consumed the request's own deadline: answer
                # structured, eject (the probe readmits a merely-slow
                # replica on its next success), do NOT replay. The elapsed
                # time IS the client-observed latency — it belongs in the
                # window (the autoscaler's p99 must see the timeouts)
                self._router_latency.observe(time.monotonic() - t_start)
                self.eject(replica, f"timeout:{type(e).__name__}")
                with self._lock:
                    self.r_upstream_timeout += 1
                return 504, json.dumps({
                    "error": SHED_UPSTREAM_TIMEOUT,
                    "replica": replica.index,
                    "detail": f"{type(e).__name__}: {e}",
                }).encode()
            finally:
                self.release_backend(replica)
            if not leg or status != 200:
                # a leg's 200 is an intermediate hop (the fan-out
                # observes the end-to-end total); its non-200 passes
                # through as the client's final answer
                self._router_latency.observe(time.monotonic() - t_start)
            with self._lock:
                if status == 200:
                    if not leg:
                        self.r_ok += 1
                        if attempt:
                            self.r_retry_ok += 1
                else:
                    self.r_passthrough_error += 1
            return status, data
        # both attempts failed: the client-observed wait is real and the
        # autoscaler's p99 must see it, like the timeout/deadline paths
        self._router_latency.observe(time.monotonic() - t_start)
        with self._lock:
            self.r_upstream_error += 1
        return 502, json.dumps({
            "error": SHED_UPSTREAM_ERROR,
            "detail": f"both attempts failed; last: {last_err}",
            "retry_after_ms": round(self.policy.probe_secs * 1e3, 1),
        }).encode()

    def _knn_fanout(self, body: bytes) -> tuple[int, bytes]:
        """Sharded /v1/knn (ISSUE 20): embed ONCE through the normal
        routed path, scatter the embedding to one healthy owner of every
        ANN shard as a `candidates` probe, merge the per-shard rerank
        lists and vote in pure python (this module is stdlib-only by
        contract — mocolint R11 — so the replica-side numpy vote in
        serve/ann.py is REIMPLEMENTED here, byte-equivalent tie-breaks
        and all). The whole scatter runs under the request's own
        deadline; shards that miss it are dropped and the answer is
        flagged `partial: true` — a degraded answer beats a stall."""
        t_start = time.monotonic()
        deadline = t_start + self._deadline_s(body)
        with self._lock:
            self.r_knn_fanout += 1
        status, data = self._routed_request("/v1/embed", body, leg=True)
        if status != 200:
            return status, data  # the leg already counted the shed
        try:
            embedding = json.loads(data)["embedding"]
        except (ValueError, KeyError, json.JSONDecodeError):
            self._router_latency.observe(time.monotonic() - t_start)
            with self._lock:
                self.r_upstream_error += 1
            return 502, json.dumps({
                "error": SHED_UPSTREAM_ERROR,
                "detail": "embed leg returned a malformed body",
            }).encode()
        # one least-outstanding healthy owner per shard, slots reserved
        # under the lock exactly like pick_backend
        targets: dict[int, ReplicaState] = {}
        with self._lock:
            for r in self.replicas:
                if (r.healthy and not r.draining and not r.abandoned
                        and r.proc is not None and r.shard is not None):
                    cur = targets.get(r.shard)
                    if cur is None or ((r.outstanding, r.index)
                                       < (cur.outstanding, cur.index)):
                        targets[r.shard] = r
            for r in targets.values():
                r.outstanding += 1
        if not targets:
            self._router_latency.observe(time.monotonic() - t_start)
            return self._shed_no_backend()
        probe = json.dumps({"candidates": True,
                            "embedding": embedding}).encode()
        results: dict[int, dict] = {}
        res_lock = threading.Lock()

        def one_shard(shard: int, r: ReplicaState) -> None:
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0.01:
                    return  # this shard missed the budget: partial
                st, raw = self._forward(r, "/v1/knn", probe, remaining)
                if st != 200:
                    return
                ans = json.loads(raw)
                if not isinstance(ans.get("candidates"), list):
                    return
                with res_lock:
                    results[shard] = ans
            except (OSError, http.client.HTTPException, ValueError):
                self.eject(r, "knn_fanout")
            finally:
                self.release_backend(r)

        threads = [
            threading.Thread(target=one_shard, args=(s, r), daemon=True,
                             name=f"knn-fanout-s{s}")
            for s, r in sorted(targets.items())
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.0) + 0.05)
        with res_lock:
            answers = dict(results)
        elapsed = time.monotonic() - t_start
        if not answers:
            self._router_latency.observe(elapsed)
            expired = time.monotonic() >= deadline
            with self._lock:
                if expired:
                    self.r_deadline_router += 1
                else:
                    self.r_upstream_error += 1
            if expired:
                return 504, json.dumps({
                    "error": SHED_DEADLINE_ROUTER,
                    "detail": "no ANN shard answered inside the "
                              "fan-out deadline",
                }).encode()
            return 502, json.dumps({
                "error": SHED_UPSTREAM_ERROR,
                "detail": "every ANN shard leg failed",
            }).encode()
        first = next(iter(answers.values()))
        k = int(first.get("k") or 200)
        temperature = float(first.get("temperature") or 0.07)
        merged = []
        for shard in sorted(answers):
            for cand in answers[shard]["candidates"]:
                merged.append((float(cand[0]), int(cand[1])))
        # global top-k across shards; ties broken toward the LOWER label
        # — the same (−sim, label) order AnnShard.search emits, so a
        # 1-shard fan-out reproduces the replica-local answer exactly
        merged.sort(key=lambda c: (-c[0], c[1]))
        votes: dict[int, float] = {}
        for sim, label in merged[:k]:
            votes[label] = votes.get(label, 0.0) + math.exp(
                sim / max(temperature, 1e-8))
        # max() keeps the FIRST maximum while scanning ascending labels:
        # lowest label wins ties, matching np.argmax in ann.vote
        pred = max(sorted(votes), key=lambda lab: votes[lab])
        partial = len(answers) < self.ann_shards
        self._router_latency.observe(elapsed)
        self._knn_merge.observe(elapsed)
        with self._lock:
            self.r_ok += 1
            if partial:
                self.r_knn_partial += 1
        return 200, json.dumps({
            "class": int(pred),
            "cached": False,
            "partial": partial,
            "shards": self.ann_shards,
            "shards_answered": len(answers),
        }).encode()

    def _forward(self, r: ReplicaState, path: str, body: bytes,
                 timeout_s: float) -> tuple[int, bytes]:
        """One attempt against one replica. A FRESH connection per
        attempt: a dead replica then fails at connect() — a clean,
        immediately-retryable signal — instead of a half-dead pooled
        socket ambiguously timing out."""
        conn = http.client.HTTPConnection(r.host, r.port,
                                          timeout=max(timeout_s, 0.01))
        try:
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _deadline_s(self, body: bytes) -> float:
        """The request's own deadline_ms when present, else the router
        default. The substring pre-check keeps the common no-deadline
        path from paying a JSON parse of a ~200 KB image body."""
        if b'"deadline_ms"' in body:
            try:
                v = json.loads(body).get("deadline_ms")
                if v:
                    return min(max(float(v) / 1e3, 0.05), 600.0)
            except (ValueError, json.JSONDecodeError):
                pass  # malformed body: the replica answers 400 either way
        return self.policy.request_timeout_s

    def _shed_no_backend(self) -> tuple[int, bytes]:
        now = time.monotonic()
        emit = False
        with self._lock:
            self.r_shed_no_backend += 1
            if now - self._last_shed_event > 5.0:  # rate-limited event
                self._last_shed_event = now
                emit = True
        if emit:
            self._emit("no_backend", healthy=0,
                       sheds=self.r_shed_no_backend)
        retry_ms = round(
            max(self.policy.probe_secs, self.policy.backoff_base_secs)
            * 1e3, 1,
        )
        return 503, json.dumps({
            "error": SHED_NO_BACKEND,
            "retry_after_ms": retry_ms,
        }).encode()

    # -- replica lifecycle ---------------------------------------------------
    def _launch(self, r: ReplicaState) -> None:
        with self._lock:
            pretrained = self._current_pretrained
            target = self._target_step
            bank = self._good_bank
        try:
            # sharded-ANN fleets (ISSUE 20) pin the replica's cell
            # partition into the argv alongside the ISSUE 16 pair: a
            # relaunched replica must come back serving ITS shard
            argv = self._child_argv(r.index, r.port, r.telemetry_dir,
                                    pretrained, bank, r.shard)
        except TypeError:
            try:
                # dual-swap fleets (ISSUE 16) pin the deployed BANK into
                # the relaunch argv alongside the weights: a replica
                # dying after a dual swap must boot on the (weights,
                # bank) pair, never new weights over its boot-time bank
                # (cross-space answers)
                argv = self._child_argv(r.index, r.port, r.telemetry_dir,
                                        pretrained, bank)
            except TypeError:
                # 4-arg child_argv (bank-free fleets, older test stubs)
                argv = self._child_argv(r.index, r.port, r.telemetry_dir,
                                        pretrained)
        env = dict(os.environ if self._env is None else self._env)
        env.update(self.tracer.child_env())
        env.update(self._replica_env.get(r.index, {}))
        log_file = open(os.path.join(r.telemetry_dir, "child.log"), "ab")
        try:
            proc = subprocess.Popen(argv, stdout=log_file,
                                    stderr=subprocess.STDOUT, env=env)
        finally:
            log_file.close()  # the child holds its own descriptor
        now = time.monotonic()
        with self._lock:
            r.proc = proc
            r.pid = proc.pid
            r.launches += 1
            r.launched_at = now
            r.last_ok_life = None
            r.ever_healthy_life = False
            r.healthy = False
            r.kill_phase = None
            r.relaunch_at = None
            r.expected_exit = False
            # a relaunch boots on the newest deployed checkpoint (pinned
            # into argv above): it converges without a reload roll
            r.deployed_step = target
            r.reload_refused_step = -1
        self._emit("launch", replica=r.index, attempt=r.launches - 1,
                   pid=proc.pid, port=r.port, budget_left=r.budget,
                   pretrained=pretrained)

    def _try_launch(self, r: ReplicaState) -> bool:
        """RE-launch path (monitor loop, roll machine): a spawn failure
        here — the binary vanished mid-run, fd exhaustion — must abandon
        the replica loudly, never unwind the monitor thread."""
        try:
            self._launch(r)
            return True
        except OSError as e:
            with self._lock:
                r.abandoned = True
            self._emit("give_up", replica=r.index,
                       reason=f"relaunch failed to spawn: {e}")
            self._check_all_abandoned()
            return False

    def _handle_exit(self, r: ReplicaState) -> None:
        rc = r.proc.returncode
        hang = r.kill_phase is not None
        cls, detail = classify_exit(rc, hang_killed=hang)
        now = time.monotonic()
        with self._lock:
            expected = r.expected_exit
            reaping = r.reaping
            progressed = r.ever_healthy_life
            pid = r.pid
            r.proc = None
            r.healthy = False
            r.kill_phase = None
            r.expected_exit = False
            r.classifications.append(cls)
        self._emit("replica_exit", replica=r.index, pid=pid, returncode=rc,
                   classification=cls, detail=detail,
                   progressed=progressed, expected=expected)
        if expected or reaping:
            # the roll machine, stop(), or the autoscale reap owns this
            # death — a reaping replica is never relaunched, even when
            # it crashed before our SIGTERM landed (the reap removes it
            # from the table on the next monitor pass either way)
            return
        if cls in FATAL_CLASSES and cls != CLASS_CLEAN:
            # CLEAN is fatal for a RUN supervisor (the run is over); a
            # serve fleet wants N replicas — an unexpected clean exit
            # (someone SIGTERM'd a replica) restarts like any death
            with self._lock:
                r.abandoned = True
            self._emit("give_up", replica=r.index,
                       reason=f"fatal class {cls}", returncode=rc)
            self._check_all_abandoned()
            return
        delay = 0.0
        with self._lock:
            if progressed:
                r.budget = self.policy.max_restarts
                r.consecutive_failures = 0
            else:
                r.consecutive_failures += 1
                if r.budget <= 0:
                    r.abandoned = True
                else:
                    r.budget -= 1
                    delay = self.policy.backoff_secs(
                        r.consecutive_failures, self._rng
                    )
            abandoned = r.abandoned
            if not abandoned:
                r.relaunch_at = now + delay
        if abandoned:
            self._emit(
                "give_up", replica=r.index,
                reason=(f"restart budget exhausted: "
                        f"{r.consecutive_failures} consecutive "
                        f"never-healthy deaths "
                        f"(max_restarts={self.policy.max_restarts})"),
            )
            self._check_all_abandoned()
        elif delay:
            self._emit("backoff", replica=r.index, secs=round(delay, 3),
                       consecutive_failures=r.consecutive_failures,
                       budget_left=r.budget)

    def _check_all_abandoned(self) -> None:
        with self._lock:
            dead = all(r.abandoned for r in self.replicas)
            self.failed = dead
        if dead:
            self._emit("fleet_give_up",
                       reason="every replica is abandoned")

    # -- probing -------------------------------------------------------------
    def _probe(self, r: ReplicaState) -> str:
        """GET /healthz with the probe budget; returns "ok", "draining",
        or an error string."""
        conn = http.client.HTTPConnection(
            r.host, r.port, timeout=self.policy.probe_timeout_s
        )
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            resp.read()
            if resp.status == 200:
                return "ok"
            if resp.status == 503:
                return "draining"
            return f"status {resp.status}"
        except (OSError, http.client.HTTPException) as e:
            return f"{type(e).__name__}: {e}"
        finally:
            conn.close()

    def _probe_and_update(self, r: ReplicaState) -> None:
        result = self._probe(r)
        now = time.monotonic()
        event = None
        if result == "ok":
            with self._lock:
                r.last_ok_life = now
                if not r.healthy and not r.draining:
                    event = ("readmit" if r.ever_healthy_life
                             else "replica_healthy")
                    r.healthy = True
                    r.ever_healthy_life = True
            if event:
                self._emit(event, replica=r.index, pid=r.pid)
        elif result == "draining":
            # alive (an answer IS a heartbeat) but not routable
            with self._lock:
                r.last_ok_life = now
                was = r.healthy
                r.healthy = False
            if was:
                self._emit("eject", replica=r.index, reason="draining")
        else:
            self.eject(r, f"probe:{result}")

    def _check_staleness(self, r: ReplicaState, now: float) -> None:
        """The wedge killer: a replica whose socket accepts but whose
        handler never answers (or whose process is silently stuck) gets
        the SIGTERM → grace → SIGKILL escalation once its last probe
        ANSWER is older than the window."""
        if r.expected_exit or not r.alive():
            return
        if r.kill_phase == "term":
            if now - r.term_at > self.policy.term_grace_secs:
                self._emit("kill", replica=r.index, pid=r.pid,
                           reason="probe_stale", phase="sigkill")
                r.proc.kill()
                with self._lock:
                    r.kill_phase = "kill"
            return
        if r.kill_phase is not None:
            return
        ref = r.last_ok_life if r.last_ok_life is not None else r.launched_at
        window = (self.policy.health_stale_secs if r.last_ok_life is not None
                  else self.policy.startup_grace_secs)
        stale_for = now - ref
        if stale_for > window:
            self._emit("kill", replica=r.index, pid=r.pid,
                       reason="probe_stale",
                       stale_secs=round(stale_for, 3), phase="sigterm")
            r.proc.terminate()
            with self._lock:
                r.kill_phase = "term"
                r.term_at = now

    # -- rolling restart -----------------------------------------------------
    def request_rolling_restart(self) -> None:
        with self._lock:
            self._roll_requested = True
        self._emit("roll_requested")

    def rolling_restart(self, timeout_s: float = 120.0) -> bool:
        """Blocking convenience (tests, SIGHUP handler thread): request a
        roll and wait for it to finish. True when the roll completed."""
        self.request_rolling_restart()
        deadline = time.monotonic() + timeout_s
        started = False
        while time.monotonic() < deadline:
            with self._lock:
                rolling = self._roll is not None or self._roll_requested
            if rolling:
                started = True
            elif started:
                return True
            time.sleep(0.05)
        return False

    def _replica_by_index(self, index: int) -> ReplicaState | None:
        """Replica lookup by its STABLE index. List position stopped
        being the index once the autoscaler started appending and
        reaping replicas (ISSUE 20); None means it was reaped."""
        with self._lock:
            for r in self.replicas:
                if r.index == index:
                    return r
        return None

    def _advance_roll(self, now: float) -> None:
        with self._lock:
            if self._roll is None:
                if not self._roll_requested:
                    return
                self._roll_requested = False
                queue = [r.index for r in self.replicas
                         if not r.abandoned and not r.reaping]
                if not queue:
                    return
                self._roll = {"queue": queue, "idx": None,
                              "phase": "await", "t": now}
                begin = True
            else:
                begin = False
            roll = self._roll
        if begin:
            self._emit("roll_begin", replicas=roll["queue"])
        if roll["idx"] is None:
            if not roll["queue"]:
                # record FIRST, then publish completion: rolling_restart
                # polls `_roll is None`, and clearing first lets a caller
                # observe "roll done" before the roll_end record exists
                # (its next read of the incident log misses the event)
                self._emit("roll_end")
                with self._lock:
                    self._roll = None
                return
            idx = roll["queue"][0]
            r = self._replica_by_index(idx)
            if r is None or r.reaping:
                # reaped by the autoscaler since roll-begin: it is on
                # its way out of the table — nothing to roll
                with self._lock:
                    roll["queue"].pop(0)
                self._emit("roll_replica", replica=idx, phase="skipped",
                           reason="reaped")
                return
            if r.abandoned:
                # abandoned since roll-begin: it will never come alive —
                # skip it, or the roll (and every future roll) wedges
                # waiting on a replica nobody will relaunch
                with self._lock:
                    roll["queue"].pop(0)
                self._emit("roll_replica", replica=idx, phase="skipped",
                           reason="abandoned")
                return
            # capacity guard (never below N−1): take the next replica out
            # only while every OTHER active replica is in rotation
            with self._lock:
                others_ok = all(
                    c.healthy for c in self.replicas
                    if c.index != idx and not c.abandoned
                    and not c.reaping
                )
            if not others_ok or not r.alive():
                return  # wait for the fleet to be whole first
            with self._lock:
                roll["queue"].pop(0)
                roll["idx"] = idx
                roll["phase"] = "wait_exit"
                roll["t"] = now
                r.draining = True      # router stops picking it NOW
                r.expected_exit = True
            self._emit("roll_replica", replica=idx, phase="drain")
            r.proc.terminate()         # serve.py drains + exits EXIT_OK
            return
        r = self._replica_by_index(roll["idx"])
        if r is None:
            with self._lock:
                roll["idx"] = None  # reaped mid-roll: move on
            return
        if roll["phase"] == "wait_exit":
            if r.proc is None:         # _handle_exit consumed the death
                with self._lock:
                    r.draining = False
                if not self._try_launch(r):
                    self._emit("roll_abort", replica=r.index,
                               reason="relaunch failed to spawn")
                    with self._lock:
                        self._roll = None
                    return
                with self._lock:
                    roll["phase"] = "wait_healthy"
                    roll["t"] = now
            elif (now - roll["t"] > self.policy.term_grace_secs
                    and r.alive()):
                self._emit("roll_replica", replica=r.index,
                           phase="sigkill")
                r.proc.kill()
        elif roll["phase"] == "wait_healthy":
            if r.healthy:
                self._emit("roll_replica", replica=r.index, phase="done")
                with self._lock:
                    roll["idx"] = None
            elif now - roll["t"] > self.policy.startup_grace_secs:
                # the relaunch never came up: abort the roll (capacity is
                # already degraded; the normal restart policy owns the
                # sick replica from here)
                self._emit("roll_abort", replica=r.index,
                           reason="relaunch never became healthy")
                with self._lock:
                    self._roll = None

    # -- hot reload ----------------------------------------------------------
    def _watch_loop(self) -> None:
        self._watcher.run(
            self.policy.watch_poll_secs, self._stop, self._on_new_step
        )

    def _on_new_step(self, step: int, path: str) -> None:
        with self._lock:
            self._target_step = step
            self._target_path = path
            # deliberately NOT _current_pretrained yet: the relaunch argv
            # pins a payload with no boot-time drift guard, so it only
            # ever carries VERIFIED weights (first successful guarded
            # deploy below) — a replica dying during the minutes-long
            # first reload attempt must not boot straight onto a
            # checkpoint no guard has judged; it boots on the last good
            # payload and converges via /admin/reload once healthy
        self._emit("reload_detected", step=step, path=path)
        self._reload_sync()

    def _reload_sync(self) -> None:
        """Bring every in-rotation replica to the target step, one at a
        time (the reload happens OFF the replica's request path, so
        capacity never drops during the roll). Replicas that are down or
        unhealthy converge later: on relaunch (argv pins the new
        payload) or on the next watcher pass."""
        if not self._reload_roll_lock.acquire(blocking=False):
            return  # a roll is in flight; the next pass converges
        try:
            self._reload_sync_locked()
        finally:
            self._reload_roll_lock.release()

    def _reload_sync_locked(self) -> None:
        with self._lock:
            step, path = self._target_step, self._target_path
        if path is None:
            return
        bank = self._paired_bank(step)
        if self.bank_dir and bank is None:
            return  # pair incomplete (bank still building / corrupt):
            # the step WAITS — encoder-only deployments on bank-free
            # fleets are untouched (bank_dir empty never gets here)
        for r in list(self.replicas):
            if self._stop.is_set():
                return
            with self._lock:
                skip = (r.abandoned or not r.healthy
                        or r.deployed_step >= step
                        or r.reload_refused_step >= step)
            if skip:
                continue
            ok, detail = self._post_reload(r, step, path, bank)
            if ok:
                with self._lock:
                    r.deployed_step = step
                    # known-good from the FIRST successful deploy (the
                    # replica's drift guard passed it), not only from a
                    # completed roll: with one replica down, a later
                    # quarantine must still roll the relaunch argv back
                    # to this payload, never past it to the boot weights
                    # — and only NOW may the relaunch argv pin it.
                    # The PREVIOUS pair is retained first: a bank
                    # mismatch surfacing on a LATER replica of this same
                    # roll rolls back to it, never to the bad pair.
                    if self._good_step != step:
                        self._prev_good = (self._good_pretrained,
                                           self._good_bank,
                                           self._good_step)
                    self._good_pretrained = path
                    self._good_bank = bank
                    self._good_step = step
                    self._current_pretrained = path
                self._emit("reload_replica", replica=r.index, step=step,
                           status="ok", detail=detail)
            else:
                with self._lock:
                    announce = r.reload_announced != step
                    r.reload_announced = step
                    if detail.startswith("status 409"):
                        # 409 is reload_refused ONLY (bank without a
                        # pair, ladder change — http.py maps transient
                        # load failures to 503): terminal for this step,
                        # stop re-attempting; transient failures retry
                        # on the next pass
                        r.reload_refused_step = step
                if announce:
                    self._emit("reload_failed", replica=r.index,
                               step=step, detail=detail)
                if "reload_bank_mismatch" in detail:
                    # dual swap (ISSUE 16): the replica judged the
                    # (checkpoint, bank) PAIR inconsistent (hash binding
                    # or the space-agreement probe). The verdict is
                    # deterministic — quarantine the pair as a unit, pin
                    # last-known-good, roll back half-swapped replicas
                    self._quarantine_pair(step, detail)
                    return
                if "reload_collapsed" in detail:
                    # drift guard (ISSUE 13): the replica judged the
                    # CHECKPOINT collapsed (degenerate probe embeddings),
                    # not its own config — quarantine the step dir so no
                    # other replica, relaunch argv, or later fleet ever
                    # promotes it, and stop targeting it
                    self._quarantine_collapsed(step, detail)
                    return
        with self._lock:
            done = all(
                r.deployed_step >= step
                for r in self.replicas if not r.abandoned
            ) and self._announced_step < step
            if done:
                self._announced_step = step
        if done:
            self._emit("reload_done", step=step, path=path,
                       replicas=self.n_replicas)

    def _quarantine_collapsed(self, step: int, detail: str) -> None:
        """A replica's reload drift guard judged step's checkpoint
        COLLAPSED (degenerate probe embeddings — ISSUE 13). The refusal
        is deterministic (same probe batch, same weights), so one
        replica's verdict stands for the fleet: quarantine the step dir
        (never re-discovered, never promoted by a later fleet), drop it
        as the reload target, and roll the relaunch argv back to the
        last known-good payload so a replica dying NOW does not boot on
        the refused weights."""
        with self._lock:
            if self._target_step == step:
                self._target_path = None
                self._current_pretrained = self._good_pretrained
        if self._watcher is not None:
            self._watcher.quarantine(
                step, f"reload drift guard: {detail[:160]}"
            )
        if self.bank_dir:
            # the pair dies as a unit: a bank built by a collapsed
            # checkpoint's encoder is as unusable as the weights
            self._quarantine_bank(step, "paired checkpoint collapsed")
        log_event(
            "fleet",
            f"checkpoint step {step} refused by the reload drift guard "
            f"(collapsed probe embeddings); quarantined — the fleet "
            f"keeps serving the previous weights",
        )

    # -- versioned-bank lifecycle (ISSUE 16) ---------------------------------
    def _paired_bank(self, step: int) -> str | None:
        """The verified bank payload paired with checkpoint `step`, or
        None when the pair is incomplete. stdlib-only (mocolint R11):
        the integrity hash check, not numpy, decides eligibility here —
        the replica's space-agreement probe is the deep check.

        A MISSING manifest means the build is still in flight (the
        builder writes it last): the step waits and a deduped
        `bank_waiting` event carries how far serving lags. A manifest
        that fails verification quarantines the bank immediately."""
        if not self.bank_dir:
            return None
        if step in self._bad_banks:
            return None
        if not os.path.exists(manifest_path(self.bank_dir, step)):
            with self._lock:
                announce = self._bank_waiting_step != step
                self._bank_waiting_step = step
                good_step = self._good_step
            if announce:
                self._emit_record(
                    "bank", "bank_waiting", step=step,
                    age_steps=(step - good_step if good_step >= 0
                               else None),
                    detail="no bank manifest yet — build in flight?",
                )
            return None
        if step not in self._bank_verified:
            reason = verify_step(self.bank_dir, step)
            if reason is not None:
                self._bad_banks.add(step)
                self._quarantine_bank(
                    step, f"bank manifest verification failed: {reason}"
                )
                return None
            self._bank_verified.add(step)
        step_dir = os.path.join(self.bank_dir, str(step))
        try:
            names = sorted(
                f for f in os.listdir(step_dir) if f.endswith(".npz")
            )
        except OSError:
            return None
        return os.path.join(step_dir, names[0]) if names else None

    def _quarantine_pair(self, step: int, detail: str) -> None:
        """A replica's space-agreement check judged the (checkpoint,
        bank) pair INCONSISTENT. The verdict is deterministic (seeded
        probe rows, content-hashed artifacts), so one replica's verdict
        stands for the fleet: quarantine BOTH halves as a unit, restore
        the pre-roll last-known-good pair, and roll back any replica
        that already swapped onto the bad pair."""
        with self._lock:
            if self._good_step == step and self._prev_good is not None:
                # a half-swapped roll advanced known-good onto the bad
                # pair before the mismatch surfaced: un-advance it
                (self._good_pretrained, self._good_bank,
                 self._good_step) = self._prev_good
            if self._target_step == step:
                self._target_path = None
                self._target_step = max(self._good_step, -1)
            self._current_pretrained = self._good_pretrained
        self._emit_record("bank", "quarantine", step=step,
                          detail=detail[:200])
        if self._watcher is not None:
            self._watcher.quarantine(
                step, f"bank/encoder space mismatch: {detail[:160]}"
            )
        self._quarantine_bank(step, "pair failed the space-agreement "
                                    "check")
        self._rollback_half_swapped(step)
        log_event(
            "fleet",
            f"(checkpoint, bank) pair for step {step} failed the "
            f"space-agreement check; quarantined as a unit — the fleet "
            f"keeps serving the last-known-good pair",
        )

    def _quarantine_bank(self, step: int, reason: str) -> None:
        """Move `<bank_dir>/<step>` to `.quarantine/` and drop its
        manifest — the PR 4 preflight pattern the checkpoint watcher
        uses, applied to the bank half of a condemned pair. Best-effort:
        filesystem errors are emitted, never raised into the roll."""
        if not self.bank_dir:
            return
        self._bad_banks.add(step)
        self._bank_verified.discard(step)
        src = os.path.join(self.bank_dir, str(step))
        if not os.path.exists(src):
            return
        try:
            qdir = os.path.join(self.bank_dir, QUARANTINE_DIRNAME)
            os.makedirs(qdir, exist_ok=True)
            target = os.path.join(qdir, str(step))
            if os.path.exists(target):
                target = f"{target}.{int(time.time())}"
            os.rename(src, target)
            try:
                os.remove(manifest_path(self.bank_dir, step))
            except OSError:
                pass
            self._emit_record("bank", "bank_quarantine", step=step,
                              reason=reason, moved_to=target)
        except OSError as e:
            self._emit_record("bank", "bank_quarantine_error", step=step,
                              detail=f"{type(e).__name__}: {e}")

    def _rollback_half_swapped(self, step: int) -> None:
        """Return every replica already swapped onto the condemned pair
        to the last-known-good one. With a good pair on record the
        rollback is itself a dual swap (reload POST — zero downtime); a
        fleet condemned on its FIRST roll has no reloadable good pair,
        so the replica restarts onto its boot-time (weights, bank) argv
        — capacity dips to N−1 briefly, correctness never."""
        with self._lock:
            good = (self._good_pretrained, self._good_bank,
                    self._good_step)
            victims = [r for r in self.replicas
                       if not r.abandoned and r.deployed_step >= step]
        for r in victims:
            if good[0] is not None and good[2] >= 0:
                ok, detail = self._post_reload(r, good[2], good[0],
                                               good[1])
                if ok:
                    with self._lock:
                        r.deployed_step = good[2]
                    self._emit_record("bank", "rollback", replica=r.index,
                                      from_step=step, to_step=good[2],
                                      mode="reload")
                    continue
                self._emit("reload_failed", replica=r.index,
                           step=good[2],
                           detail=f"rollback failed: {detail}")
            # no reloadable good pair (or the rollback POST failed):
            # restart the replica onto its boot argv — the launch path
            # pins _current_pretrained, already reset to known-good
            with self._lock:
                r.deployed_step = -1
                alive = r.alive()
            if alive:
                r.proc.terminate()
            self._emit_record("bank", "rollback", replica=r.index,
                              from_step=step, to_step=good[2],
                              mode="restart")

    def _post_reload(self, r: ReplicaState, step: int, path: str,
                     bank: str | None = None) -> tuple[bool, str]:
        req = {"pretrained": path, "step": step}
        if bank is not None:
            # the dual swap: the replica verifies the pair (manifest,
            # checkpoint-hash binding, space-agreement probe) and rolls
            # engine + bank under one generation bump
            req["bank"] = bank
            req["bank_step"] = step
        body = json.dumps(req).encode()
        conn = http.client.HTTPConnection(
            r.host, r.port, timeout=self.policy.reload_timeout_s
        )
        try:
            conn.request("POST", "/admin/reload", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 200:
                return True, data.decode("utf-8", errors="replace")[:200]
            return False, (f"status {resp.status}: "
                           + data.decode("utf-8", errors="replace")[:200])
        except (OSError, http.client.HTTPException) as e:
            return False, f"{type(e).__name__}: {e}"
        finally:
            conn.close()

    # -- the monitor loop ----------------------------------------------------
    def _monitor_loop(self) -> None:
        poll = max(min(self.policy.probe_secs / 2.0, 0.5), 0.02)
        while not self._stop.is_set():
            now = time.monotonic()
            # snapshot: the autoscaler appends and reaps mid-iteration
            for r in list(self.replicas):
                if r.abandoned:
                    continue
                if r.reaping:
                    self._advance_reap(r, now)
                    continue
                if r.proc is None:
                    with self._lock:
                        due = (r.relaunch_at is not None
                               and now >= r.relaunch_at)
                    if due:
                        self._try_launch(r)
                    continue
                if r.proc.poll() is not None:
                    self._handle_exit(r)
                    continue
                if now - getattr(r, "_last_probe", 0.0) \
                        >= self.policy.probe_secs:
                    r._last_probe = now
                    self._probe_and_update(r)
                self._check_staleness(r, now)
            self._advance_roll(time.monotonic())
            # a reload target may predate a replica's recovery: converge
            # — on a THROWAWAY thread, never this one: one reload blocks
            # for a checkpoint load + ladder warmup, and the monitor
            # must keep probing/killing/relaunching the OTHER replicas
            # meanwhile (_reload_sync itself no-ops when a roll is
            # already in flight, so the spawn is cheap and un-duplicated)
            with self._lock:
                need_sync = any(
                    not r.abandoned and r.healthy
                    and r.deployed_step < self._target_step
                    and r.reload_refused_step < self._target_step
                    for r in self.replicas
                ) if self._target_path else False
            if need_sync and not self._reload_roll_lock.locked():
                threading.Thread(target=self._reload_sync, daemon=True,
                                 name="fleet-reload-converge").start()
            now = time.monotonic()
            if now - self._last_stats_event >= self.policy.stats_every_secs:
                with self._lock:
                    self._last_stats_event = now
                self._emit_router_stats()
                # the autoscaler consumes the SAME windowed stream it
                # just emitted: one cadence, one source of truth
                self._autoscale_tick(now)
            self._stop.wait(poll)

    # -- autoscaling (ISSUE 20) ----------------------------------------------
    def _autoscale_tick(self, now: float) -> None:
        """One controller observation per stats emit. The snapshot fed
        to the controller is the SAME shape `_emit_router_stats` just
        wrote, so an operator replaying events.jsonl through an
        AutoscaleController reproduces every decision."""
        if self.policy.autoscale_max <= 0:
            return
        with self._lock:
            stats = self._router_counters()
            stats["healthy"] = sum(
                1 for r in self.replicas
                if r.healthy and not r.draining and not r.abandoned
            )
            stats["outstanding"] = sum(
                r.outstanding for r in self.replicas)
        if self._router_latency.count:
            stats["latency_ms"] = self._router_latency.percentiles_ms()
        decision = self._autoscaler.observe(stats, now)
        if decision is None:
            return
        action, reason = decision
        if action == "up":
            self._scale_up(reason)
        else:
            self._scale_down(reason)

    def _active_replicas(self) -> list[ReplicaState]:
        # caller holds the lock
        return [r for r in self.replicas
                if not r.abandoned and not r.reaping]

    def _scale_up(self, reason: str) -> None:
        with self._lock:
            if len(self._active_replicas()) >= self.policy.autoscale_max:
                return  # at the replica budget: breach stays visible in
                # router_stats; capacity does not follow
            index = self._next_index
            self._next_index += 1
        port = (self._base_port + index if self._base_port
                else pick_free_port(self.host))
        rdir = os.path.join(self.telemetry_dir, f"replica{index}")
        try:
            os.makedirs(rdir, exist_ok=True)
        except OSError as e:
            self._emit("autoscale_error", replica=index,
                       detail=f"cannot create {rdir!r}: {e}")
            return
        r = ReplicaState(index, self.host, port, rdir,
                         self.policy.max_restarts)
        if self.ann_shards:
            r.shard = index % self.ann_shards
        with self._lock:
            self.replicas.append(r)
            total = len(self.replicas)
        self._emit("autoscale_up", replica=index, port=port,
                   shard=r.shard, reason=reason, replicas=total)
        self._try_launch(r)

    def _scale_down(self, reason: str) -> None:
        with self._lock:
            active = self._active_replicas()
            # the floor: operator minimum, and never below one healthy
            # owner per ANN cell partition (shard cover)
            floor = max(self.policy.autoscale_min, self.ann_shards, 1)
            if len(active) <= floor:
                return

            def reapable(v: ReplicaState) -> bool:
                if not v.healthy or v.draining:
                    return False
                if v.shard is None:
                    return True
                # shard-cover guard: never reap a partition's last
                # healthy owner
                return any(
                    c is not v and c.shard == v.shard and c.healthy
                    and not c.draining
                    for c in active
                )

            cands = [r for r in active if reapable(r)]
            if not cands:
                return
            victim = max(cands, key=lambda r: r.index)
            victim.reaping = True
            victim.draining = True  # the router stops picking it NOW
            total = len(self.replicas)
        self._emit("autoscale_down", replica=victim.index,
                   shard=victim.shard, reason=reason, replicas=total)

    def _advance_reap(self, r: ReplicaState, now: float) -> None:
        """Drain-then-reap, one monitor pass at a time: `draining`
        already keeps new picks away, so wait for the router's
        in-flight count to hit zero, SIGTERM (serve.py finishes
        accepted work and exits cleanly), escalate a straggler past
        the grace window, and drop the replica from the table once the
        process is gone. Zero accepted requests lost by construction."""
        if r.proc is None:
            with self._lock:
                if r in self.replicas:
                    self.replicas.remove(r)
                remaining = len(self.replicas)
            self._emit("autoscale_reaped", replica=r.index,
                       replicas=remaining)
            return
        if r.proc.poll() is not None:
            self._handle_exit(r)  # reaping => no relaunch scheduled
            return
        term = kill = False
        with self._lock:
            if not r.expected_exit:
                if r.outstanding == 0:
                    r.expected_exit = True
                    r.term_at = now
                    term = True
            elif now - r.term_at > self.policy.term_grace_secs:
                r.term_at = now
                kill = True
        if term:
            r.proc.terminate()
        elif kill:
            self._emit("kill", replica=r.index, pid=r.pid,
                       reason="reap_straggler", phase="sigkill")
            r.proc.kill()

    def _emit_router_stats(self, final: bool = False) -> None:
        """The autoscaler input record (ISSUE 12 satellite): one
        `kind:"fleet", event:"router_stats"` line on a fixed time
        cadence (`stats_every_secs`, plus one `final` at stop). STABLE
        SCHEMA — obsd and ROADMAP 2b's autoscaler key on it:

          requests/ok/retries/retry_ok        cumulative counters
          shed_no_backend / upstream_timeout /
          upstream_error / shed_deadline_router /
          passthrough_non_200                 cumulative per-code sheds
          outstanding                         in-flight depth gauge NOW
          healthy / replicas                  rotation-eligible / total
          latency_ms {p50,p95,p99} + window   answered-request latency
                                              over the trailing ring
                                              (absent until any answer)
          interval_s                          the emit cadence, so a
                                              consumer can rate-convert
                                              counter deltas

        ISSUE 20 ADDITIVE keys (a pre-20 consumer keeps working):

          requests_interactive /
          requests_batch                      cumulative per-tier demand
          knn_fanout / knn_partial            cumulative sharded-kNN
                                              scatters / partial answers
          ann_shards                          cell-partition count
                                              (absent on ann-free fleets)
          knn_merge_ms {p50,p95,p99}          end-to-end fan-out latency
                                              (absent until any fan-out)

        Consumers take DELTAS between consecutive records for rates (the
        counters are cumulative — a last-snapshot fold stays valid)."""
        with self._lock:
            counters = self._router_counters()
            healthy = sum(
                1 for r in self.replicas
                if r.healthy and not r.draining and not r.abandoned
            )
            outstanding = sum(r.outstanding for r in self.replicas)
        extras: dict = {
            "outstanding": outstanding,
            "replicas": len(self.replicas),
            "interval_s": self.policy.stats_every_secs,
        }
        if self._router_latency.count:
            extras["latency_ms"] = self._router_latency.percentiles_ms()
            extras["window"] = self._router_latency.count
        if self.ann_shards:
            extras["ann_shards"] = self.ann_shards
        if self._knn_merge.count:
            extras["knn_merge_ms"] = self._knn_merge.percentiles_ms()
        self._emit("router_stats", final=final, healthy=healthy,
                   **counters, **extras)
