"""Request-path orchestration: admission → cache → batcher → engine
(ISSUE 5 tentpole).

`EmbedService` is the front end's single entry point. One `embed()` call
walks: shape/dtype validation, the content-hash embedding LRU, the
micro-batcher's bounded admission queue, a bucketed device call, and the
telemetry instruments — returning a feature row or raising one of the
structured rejections from serve/batcher.py. `classify()` rides the same
path and finishes with a weighted-kNN vote against a precomputed feature
bank (`ops/knn.knn_predict`, the InstDisc protocol the pretrain monitor
uses).

Telemetry: latency / batch-occupancy / queue-wait histograms feed
cumulative `kind: "serve"` snapshot records into the SAME events.jsonl
stream training writes (`MetricsRegistry`), emitted every
`snapshot_every` batches and once at drain — `tools/telemetry_report.py`
renders the last snapshot as its `serve:` section.

Hot weight reload (ISSUE 10): `reload(path)` builds a SECOND engine from
a new checkpoint via the configured factory, warms its whole bucket
ladder off-path (the live engine keeps serving throughout), then swaps
the serving state in one reference assignment. The batcher calls the
engine through `_run_batch`, which reads the serving state exactly once
per coalesced batch — so every micro-batch executes entirely on one
engine and the swap lands BETWEEN batches, never inside one. The
content-hash embedding cache is cleared at swap (its rows are functions
of the old weights); requests in flight during the swap simply ride
whichever engine their batch drew — both answer correctly for their
weights, and nothing is dropped.

Atomic dual swap (ISSUE 16): under a configured kNN bank, a reload must
carry a VERIFIED paired bank (built by tools/bank_build.py against the
same checkpoint) or it is refused — the old "never under a bank" guard
generalized to "only without a verified pair". The pair is vetted
before any engine is built (manifest integrity, checkpoint-hash
binding) and after warmup by the space-agreement check (the new engine
re-embeds the bank's recorded seeded probe rows; low cosine ⇒
`BankMismatchError`, the fleet's quarantine signal). The swap itself
publishes (engine, bank) under ONE generation bump: `_run_batch` tags
every feature row with the generation it was embedded under, and
`classify()` votes against the bank REGISTERED FOR THAT GENERATION — a
request whose embed rode the old engine across the swap votes against
the old bank, never across spaces.

Shutdown: `drain()` (SIGTERM in tools/serve.py) stops admission, lets
every accepted request finish, and flushes the final snapshot — reject
new, complete old, then exit."""

from __future__ import annotations

import threading
import time

import numpy as np

from moco_tpu.serve.batcher import MicroBatcher
from moco_tpu.serve.cache import EmbeddingCache
from moco_tpu.telemetry.registry import Histogram
from moco_tpu.utils.logging import log_event

# most-recent observations the stats histograms keep: a server runs for
# weeks — unbounded reservoirs (fine for a bounded training run) would
# grow memory and per-snapshot sort cost forever, and an operator wants
# RECENT percentiles from /stats anyway
STATS_WINDOW = 8192


class ReloadRefusedError(ValueError):
    """A hot reload that can NEVER succeed for this process's
    configuration (kNN bank configured, image_size or bucket-ladder
    change, no factory wired) — distinct from a transient load/warmup
    failure so the fleet's converge loop knows to STOP retrying
    (http.py answers 409 for refusals, 503 for retryable failures)."""


class CollapsedCheckpointError(ReloadRefusedError):
    """The reload drift guard (ISSUE 13) rejected the NEW engine: its
    embeddings of the fixed probe batch are degenerate (every probe maps
    to ~one direction — the serving face of representation collapse) or
    unrelated to the previous engine's. Terminal like every refusal, but
    the CHECKPOINT is at fault, not this process's config — the fleet
    quarantines the step dir so no replica (or later fleet) promotes it."""


class BankMismatchError(ReloadRefusedError):
    """The offered (checkpoint, bank) pair failed verification (ISSUE
    16): manifest integrity, checkpoint-hash binding, feature-dim, or
    the space-agreement probe check. Terminal like every refusal, and —
    like a collapsed checkpoint — the ARTIFACTS are at fault, not this
    process's config: the fleet quarantines the pair as a unit and rolls
    back any half-swapped replica to the last-known-good pair."""


class _TaggedRows(np.ndarray):
    """Feature rows stamped with the engine generation that embedded
    them. Slicing/viewing preserves the tag (`__array_finalize__`), so
    the per-request row the batcher peels off a coalesced batch still
    knows which generation produced it — classify() uses that to vote
    against the SAME generation's bank across a dual swap."""

    gen: int = -1

    def __array_finalize__(self, obj):
        if obj is not None:
            self.gen = getattr(obj, "gen", -1)


class _ServingState:
    """The (engine, generation) pair `_run_batch` reads in ONE attribute
    load — a dual swap replaces the whole object, so a micro-batch can
    never see the new engine with the old generation or vice versa."""

    __slots__ = ("engine", "gen")

    def __init__(self, engine, gen: int):
        self.engine = engine
        self.gen = gen


class EmbedService:
    def __init__(
        self,
        engine,
        *,
        flush_ms: float = 10.0,
        max_queue: int = 256,
        request_deadline_ms: float = 2000.0,
        cache_mb: int = 0,
        registry=None,
        snapshot_every: int = 25,
        tracer=None,
        shed_spike_min: int = 8,
        knn_bank: np.ndarray | None = None,
        knn_labels: np.ndarray | None = None,
        num_classes: int = 0,
        knn_k: int = 200,
        knn_temperature: float = 0.07,
        reload_probe: int = 8,
        reload_min_spread: float = 1e-4,
        knn_bank_meta: dict | None = None,
        bank_agreement_min: float = 0.98,
        ann=None,
        admission_tiers: bool = True,
        batch_max_queue: int | None = None,
        batch_deadline_ms: float | None = None,
    ):
        self.engine = engine
        self.feat_dim = engine.warmup()  # every bucket compiled before traffic
        self.cache = EmbeddingCache(cache_mb) if cache_mb else None
        self.registry = registry
        self.snapshot_every = max(int(snapshot_every), 1)
        self.draining = False
        self.wedged = False  # chaos wedge_at_request: the front end checks
                             # this and stops answering (fleet drill)
        self._lock = threading.Lock()
        # hot reload (ISSUE 10): the factory (path -> un-warmed engine) is
        # wired by tools/serve.py, which owns the arch/buckets config;
        # reloads serialize on their own lock so the live request path
        # never waits on a checkpoint load
        self._engine_factory = None
        self._reload_lock = threading.Lock()
        self.reloads = 0
        # reload drift guard (ISSUE 13): rows in the fixed probe batch
        # (0 disables the guard) + the spread floor under which a new
        # engine's probe embeddings count as collapsed
        self.reload_probe = int(reload_probe)
        self.reload_min_spread = float(reload_min_spread)
        self._reload_history: list[dict] = []
        self._engine_gen = 0  # bumped at every swap: an in-flight request
                              # that executed on the OLD engine must not
                              # repopulate the just-cleared cache
        self._gen_lock = threading.Lock()  # makes (gen check -> put) in
                              # embed atomic against (gen += 1 -> clear)
                              # in reload — a bare check-then-put could
                              # be descheduled across the whole swap and
                              # insert a stale row AFTER the clear
        self.requests = 0
        self.served = 0
        self._started = time.monotonic()  # uptime is a duration, not a timestamp
        self._h_latency = Histogram("serve_latency_s", window=STATS_WINDOW)
        self._h_queue_wait = Histogram("serve_queue_wait_s",
                                       window=STATS_WINDOW)
        self._request_deadline_s = float(request_deadline_ms) / 1e3
        # tracing (ISSUE 8): the batcher stamps request/flush/engine spans
        # and arms shed-spike captures; the service ticks the capture
        # window once per executed batch and surfaces the capture state on
        # /healthz + /stats
        self.tracer = tracer
        # tiered admission (ISSUE 20): interactive vs batch lanes in the
        # batcher; admission_tiers=False collapses everything onto the
        # interactive lane (tier tags are accepted but ignored)
        self.admission_tiers = bool(admission_tiers)
        self.batcher = MicroBatcher(
            self._run_batch,
            buckets=engine.buckets,
            flush_ms=flush_ms,
            max_queue=max_queue,
            default_deadline_ms=request_deadline_ms,
            on_batch=self._note_batch,
            tracer=tracer,
            shed_spike_min=shed_spike_min,
            batch_max_queue=batch_max_queue,
            batch_deadline_ms=batch_deadline_ms,
        )
        # dual swap (ISSUE 16): the (engine, generation) pair _run_batch
        # reads atomically, the per-generation bank registry classify()
        # resolves tagged rows against, and the versioned-bank metadata
        # (None for a plain --knn-bank npz or a bank-free service)
        self._serving = _ServingState(engine, 0)
        self._knn_by_gen: dict = {}
        self._bank_meta = knn_bank_meta
        self.bank_agreement_min = float(bank_agreement_min)
        self._bank_swaps = 0
        # kNN vote parameters survive a bank swap (and let a bank-free
        # service ADOPT a bank offered by a later dual-swap reload)
        self._knn_defaults = {
            "num_classes": int(num_classes),
            "k": int(knn_k),
            "temperature": float(knn_temperature),
        }
        self._knn = None
        if knn_bank is not None:
            if knn_labels is None or len(knn_bank) != len(knn_labels):
                raise ValueError("knn_bank needs matching knn_labels")
            self._knn = self._make_knn(knn_bank, knn_labels)
            self._knn_by_gen[0] = self._knn
            # pre-compile the kNN program too: the first classify must not
            # pay a trace under live traffic (same rule as engine.warmup)
            self._knn_predict(np.ones((1, self.feat_dim), np.float32))
        # sharded ANN (ISSUE 20): an AnnShard replaces the exact vote on
        # classify() and answers candidate probes for the fleet's fan-out
        # merge. ann=None keeps the exact path BIT-identical to before.
        if ann is not None and self._knn is None:
            raise ValueError("ann requires a configured kNN bank")
        self._ann = ann
        self._ann_by_gen: dict = {0: ann} if ann is not None else {}
        self.ann_candidate_calls = 0
        # boot-time recall probe vs exact over this shard's rows — the
        # number obsd's ann_recall_probe objective watches
        self._ann_recall = (round(ann.recall_probe(), 4)
                            if ann is not None else None)
        if self.registry is not None:
            self.registry.emit(
                "serve_start",
                image_size=engine.image_size,
                feat_dim=self.feat_dim,
                buckets=list(engine.buckets),
                flush_ms=flush_ms,
                max_queue=max_queue,
                request_deadline_ms=request_deadline_ms,
                cache_mb=cache_mb,
                knn_bank_size=0 if self._knn is None else len(self._knn["bank"]),
                ann=self._ann is not None,
            )

    def _make_knn(self, bank, labels) -> dict:
        labels = np.asarray(labels, np.int32)
        d = self._knn_defaults
        return {
            "bank": np.asarray(bank, np.float32),
            "labels": labels,
            "num_classes": int(d["num_classes"] or labels.max() + 1),
            "k": d["k"],
            "temperature": d["temperature"],
        }

    # -- the engine indirection (hot reload) ---------------------------------
    def _run_batch(self, images_u8: np.ndarray) -> np.ndarray:
        """The batcher's executor. Reads `self._serving` EXACTLY once per
        coalesced batch (one GIL-atomic attribute load), so a concurrent
        `reload()` swap can only land between micro-batches — every batch
        runs whole on one engine, never half-and-half. Rows come back
        generation-tagged so classify() can vote against the SAME
        generation's bank even when a dual swap landed mid-flight."""
        serving = self._serving
        rows = np.asarray(serving.engine.embed(images_u8))
        tagged = rows.view(_TaggedRows)
        tagged.gen = serving.gen
        return tagged

    # -- request paths -------------------------------------------------------
    def embed(self, image: np.ndarray,
              deadline_s: float | None = None,
              tier: str = "interactive") -> tuple[np.ndarray, bool]:
        """One request: returns `(embedding, cache_hit)` or raises a
        `RejectionError` subclass (overloaded / deadline_exceeded /
        draining) — the caller always gets a decision. `tier` picks the
        admission lane (ISSUE 20): "batch" work sheds independently of
        interactive traffic."""
        if not self.admission_tiers:
            tier = "interactive"
        image = self._validate(image)
        with self._lock:
            self.requests += 1
            n_requests = self.requests
        self._maybe_chaos(n_requests)
        t0 = time.monotonic()
        key = None
        if self.cache is not None:
            key = EmbeddingCache.key_for(image)
            hit = self.cache.get(key)
            if hit is not None:
                with self._lock:
                    self.served += 1
                self._h_latency.observe(time.monotonic() - t0)
                return hit, True
        gen = self._engine_gen  # which engine this request is paying for
        pending = self.batcher.submit(image, deadline_s, tier=tier)
        # generous slack over the request deadline: the batcher ALWAYS
        # resolves accepted requests, so this only guards a dead flusher
        result = pending.wait(
            timeout=(deadline_s or self._request_deadline_s) + 30.0
        )
        self._h_latency.observe(time.monotonic() - t0)
        if self.cache is not None:
            row_gen = getattr(result, "gen", gen)  # the generation that
            # actually embedded this row (tagged in _run_batch); falls
            # back to the admission-time gen for untagged stub engines
            with self._gen_lock:
                # a reload swapped engines while this request was in
                # flight: its row came from the OLD weights and must not
                # repopulate the just-cleared cache as a forever-stale
                # hit. Under the lock the check and the put are one unit
                # against reload's increment-then-clear.
                if row_gen == self._engine_gen:
                    self.cache.put(key, result)
        with self._lock:
            self.served += 1
        return result, False

    def classify(self, image: np.ndarray,
                 deadline_s: float | None = None,
                 tier: str = "interactive") -> tuple[int, np.ndarray, bool]:
        """kNN-classify against the precomputed feature bank: returns
        `(class_id, embedding, cache_hit)`. With an ANN index configured
        the vote runs over the index's probed cells (this replica's
        shard view); without one the exact `ops/knn` path is untouched —
        bit-identical to the pre-ANN `/v1/knn`."""
        if self._knn is None:
            raise ValueError(
                "no kNN feature bank configured (serve with --knn-bank)"
            )
        embedding, cached = self.embed(image, deadline_s, tier=tier)
        # generation-consistent vote (ISSUE 16): the row is tagged with
        # the generation that embedded it; vote against THAT generation's
        # bank. A cache hit is always current-generation (the cache is
        # cleared inside the swap's gen bump), and a row whose generation
        # left the registry (two swaps inside one request lifetime) falls
        # back to the current bank — never a silent cross-space vote
        # under a single swap.
        row_gen = getattr(embedding, "gen", None)
        if self._ann is not None:
            ann = self._ann_by_gen.get(row_gen, self._ann) \
                if row_gen is not None else self._ann
            pred, _n = ann.classify(np.asarray(embedding))
            return int(pred), embedding, cached
        knn = self._knn_by_gen.get(row_gen, self._knn) \
            if row_gen is not None else self._knn
        pred = self._knn_predict(embedding[None, :], knn=knn)
        return int(pred[0]), embedding, cached

    def ann_candidates(self, embedding) -> dict:
        """One shard's answer to the fleet router's `/v1/knn` fan-out
        (ISSUE 20): top candidates among the cells THIS replica owns,
        as plain JSON-able (sim, label) pairs plus the vote parameters —
        the stdlib-only router merges across shards and votes without
        ever importing numpy or serve/ann.py."""
        if self._ann is None:
            raise ValueError(
                "no ANN index configured (serve with --ann-cells and a "
                "bank built via tools/bank_build.py --ann-cells)"
            )
        q = np.asarray(embedding, np.float32).reshape(-1)
        if q.shape[0] != self.feat_dim:
            raise ValueError(
                f"embedding dim {q.shape[0]} != feat_dim {self.feat_dim}"
            )
        ann = self._ann
        sims, labels, _rows = ann.search(q)
        with self._lock:
            self.ann_candidate_calls += 1
        return {
            "candidates": [[float(s), int(lab)]
                           for s, lab in zip(sims, labels)],
            "temperature": ann.temperature,
            "k": int(self._knn["k"]) if self._knn is not None
            else ann.rerank,
            "num_classes": ann.num_classes,
            "shard": ann.shard,
            "shards": ann.shards,
        }

    def _knn_predict(self, features: np.ndarray,
                     knn: dict | None = None) -> np.ndarray:
        from moco_tpu.ops.knn import knn_predict

        k = self._knn if knn is None else knn
        return np.asarray(knn_predict(
            features, k["bank"], k["labels"], k["num_classes"],
            k=k["k"], temperature=k["temperature"],
        ))

    def _validate(self, image) -> np.ndarray:
        image = np.asarray(image)
        s = self.engine.image_size
        if image.shape != (s, s, 3) or image.dtype != np.uint8:
            raise ValueError(
                f"expected one [{s}, {s}, 3] uint8 image, got "
                f"{image.shape} {image.dtype}"
            )
        return image

    def _maybe_chaos(self, n_requests: int) -> None:
        """Fleet-drill faults (ISSUE 10): a SIGKILL or an accepting-but-
        not-answering wedge at the configured request count. Imported
        lazily: chaos is a drill facility, not a request-path dependency."""
        from moco_tpu.resilience.chaos import active_chaos

        plan = active_chaos()
        if plan is None:
            return
        plan.maybe_kill_request(n_requests)  # no return: SIGKILL
        if plan.maybe_wedge_request(n_requests):
            self.wedged = True  # the front end hangs every LATER request

    # -- hot weight reload (ISSUE 10) ----------------------------------------
    def set_engine_factory(self, factory) -> None:
        """`factory(checkpoint_path) -> EmbeddingEngine` (un-warmed).
        tools/serve.py wires `EmbeddingEngine.from_checkpoint` with its
        arch/buckets config; tests wire in-process builders."""
        self._engine_factory = factory

    def reload(self, pretrained: str, step: int | None = None,
               bank: str | None = None,
               bank_step: int | None = None) -> dict:
        """Build + warm a new engine from `pretrained` OFF the request
        path, then atomically swap it in (see `_run_batch`). Raises
        ValueError on any failure — the old engine keeps serving, nothing
        is dropped. Serialized: concurrent reloads queue on the lock.

        Dual swap (ISSUE 16): pass `bank` (a versioned bank npz built by
        tools/bank_build.py against the SAME checkpoint) to roll engine
        and kNN bank together under one generation bump. The pair is
        verified before the swap — manifest integrity, checkpoint-hash
        binding, feature-dim, and the post-warmup space-agreement probe —
        and any failure raises `BankMismatchError` with the old pair
        untouched. Under a configured bank, a bank-LESS reload refuses."""
        if self._engine_factory is None:
            raise ReloadRefusedError(
                "hot reload is not configured (no engine factory; serve "
                "with tools/serve.py or call set_engine_factory)"
            )
        with self._reload_lock:
            # cheap refusals FIRST: every check that needs no (or only an
            # un-warmed) engine runs before the minutes-scale ladder
            # warmup, so a refused reload — which a fleet's converge loop
            # may re-attempt — never burns a checkpoint load + compile
            if self._knn is not None and bank is None:
                # the feature bank was computed by the OLD encoder; new
                # embeddings live in a different space, so /v1/knn would
                # silently classify across spaces — refuse UNLESS the
                # reload carries a verified paired bank (the dual swap)
                e = ReloadRefusedError(
                    "hot reload is refused under a configured kNN bank "
                    "without a verified paired bank: the bank's features "
                    "were computed by the old encoder and would silently "
                    "mismatch the new embedding space — build a paired "
                    "bank with tools/bank_build.py against the new "
                    "checkpoint and reload the (pretrained, bank) pair "
                    "together"
                )
                e.bank_step = None if self._bank_meta is None \
                    else self._bank_meta.get("step")
                raise e
            new_knn = new_meta = new_ann = None
            if bank is not None:
                # the whole pair is vetted BEFORE the factory runs: a
                # doctored or torn bank must cost hashing, not a
                # checkpoint load + ladder compile
                bank_feats, bank_labels, new_meta = \
                    self._verify_bank_pair(bank, pretrained, bank_step)
                new_knn = self._make_knn(bank_feats, bank_labels)
                if self._ann is not None:
                    # under a configured ANN index the new bank must
                    # carry a verified PAIRED index (built by bank_build
                    # --ann-cells): same rule as bank-under-knn — a bank
                    # swap that silently dropped to exact (or to a stale
                    # index) would change answer semantics mid-fleet
                    new_ann = self._paired_ann(bank, bank_feats,
                                               bank_labels)
            t0 = time.monotonic()
            try:
                new_engine = self._engine_factory(pretrained)
            except (ValueError, OSError, KeyError) as e:
                raise ValueError(f"cannot load {pretrained!r}: {e}") from e
            if new_engine.image_size != self.engine.image_size:
                raise ReloadRefusedError(
                    f"reload changes image_size "
                    f"{self.engine.image_size} -> {new_engine.image_size}; "
                    "the request contract is per-process, restart instead"
                )
            if tuple(new_engine.buckets) != tuple(self.engine.buckets):
                raise ReloadRefusedError(
                    f"reload changes the bucket ladder "
                    f"{tuple(self.engine.buckets)} -> "
                    f"{tuple(new_engine.buckets)}: the micro-batcher "
                    "coalesces to the OLD ladder, so a smaller one would "
                    "overflow live batches and a different one would "
                    "compile on-path"
                )
            try:
                feat_dim = new_engine.warmup()  # whole ladder, off-path
            except (ValueError, OSError, KeyError) as e:
                raise ValueError(f"cannot load {pretrained!r}: {e}") from e
            # reload drift guard (ISSUE 13): embed one fixed probe batch
            # on BOTH engines (off-path — the live engine keeps serving)
            # and refuse a checkpoint whose probe embeddings collapsed.
            # A full lincls run is the honest quality gate; this is the
            # cheap one that catches the silent failure mode training's
            # CollapseSentinel watches for, at the promotion boundary.
            probe = self._probe_stats(new_engine)
            if probe is not None and probe["probe_spread"] < \
                    self.reload_min_spread:
                raise CollapsedCheckpointError(
                    f"reload refused: probe-batch embeddings of "
                    f"{pretrained!r} are degenerate (spread "
                    f"{probe['probe_spread']:.2e} < "
                    f"{self.reload_min_spread:.2e}; drift vs live engine "
                    f"{probe['probe_drift']:.4f}) — the checkpoint looks "
                    "collapsed; keeping the previous weights"
                )
            agreement = None
            if new_knn is not None:
                # space-agreement check (ISSUE 16, generalizing the PR 13
                # probe guard): the NEW engine re-embeds the bank's
                # recorded seeded probe rows; a bank whose manifest lies
                # about its checkpoint scores near chance and the pair is
                # refused as a unit — never half-swapped
                agreement = self._bank_agreement(new_engine, new_meta,
                                                 feat_dim, bank)
            warm_s = time.monotonic() - t0
            if new_knn is not None:
                # pre-compile the new kNN program off-path (same rule as
                # engine.warmup: the first classify after the swap must
                # not pay a trace under live traffic)
                self._knn_predict(np.ones((1, feat_dim), np.float32),
                                  knn=new_knn)
            # THE swap, one generation bump for BOTH halves: register the
            # new generation's bank, publish the new serving state (what
            # _run_batch reads), then bump the gen + clear the cache
            # under the gen lock. Rows embedded by the old engine stay
            # tagged with the old generation and keep voting against the
            # old bank; the first batch on the new state gets the new
            # pair — no interleaving yields a cross-space answer.
            new_gen = self._engine_gen + 1
            if new_knn is not None:
                self._knn_by_gen[new_gen] = new_knn
                for g in [g for g in self._knn_by_gen
                          if g < new_gen - 1]:
                    del self._knn_by_gen[g]  # keep current + previous
                if new_ann is not None:
                    self._ann_by_gen[new_gen] = new_ann
                    for g in [g for g in self._ann_by_gen
                              if g < new_gen - 1]:
                        del self._ann_by_gen[g]
            elif self._knn is not None:
                # bank-less swap on a bank-free service never gets here
                # (the refusal above); this re-registers the unchanged
                # bank under the new generation
                self._knn_by_gen[new_gen] = self._knn
            self._serving = _ServingState(new_engine, new_gen)
            with self._gen_lock:
                # cached rows are functions of the OLD weights; serving
                # them after the swap would silently mix model versions.
                # Increment + clear under the gen lock so no in-flight
                # old-engine request can slip a row in after the clear.
                self._engine_gen = new_gen
                if self.cache is not None:
                    self.cache.clear()
            self.engine = new_engine
            self.feat_dim = feat_dim
            if new_knn is not None:
                self._knn = new_knn
                self._bank_meta = new_meta
                self._bank_swaps += 1
                if new_ann is not None:
                    self._ann = new_ann
                    self._ann_recall = round(new_ann.recall_probe(), 4)
            entry = {
                "step": step,
                "pretrained": pretrained,
                "warm_s": round(warm_s, 3),
                "feat_dim": feat_dim,
            }
            if probe is not None:
                entry.update(probe)
            if new_knn is not None:
                entry["bank"] = bank
                entry["bank_step"] = new_meta.get("step") \
                    if new_meta else bank_step
                entry["bank_rows"] = len(new_knn["bank"])
                if agreement is not None:
                    entry["bank_agreement"] = round(agreement, 6)
            with self._lock:
                self.reloads += 1
                self._reload_history.append(entry)
                del self._reload_history[:-16]  # bounded: /stats payload
            log_event(
                "serve",
                f"hot-reloaded weights from {pretrained} "
                f"(step {step}, ladder warmed in {warm_s:.1f}s"
                + (f", bank step {entry['bank_step']}"
                   if new_knn is not None else "") + ")",
            )
            if self.registry is not None:
                self.registry.emit("event", event="serve_reload", **entry)
                if new_knn is not None:
                    self.registry.emit(
                        "bank", event="swap", step=step,
                        bank_step=entry["bank_step"],
                        rows=entry["bank_rows"], generation=new_gen,
                        agreement=entry.get("bank_agreement"),
                    )
            return entry

    def _verify_bank_pair(self, bank: str, pretrained: str,
                          bank_step: int | None):
        """Pre-factory vetting of an offered (checkpoint, bank) pair.
        Returns (features, labels, meta). Raises `BankMismatchError`
        (terminal — quarantine the pair) for integrity / binding
        failures, plain ValueError (retryable 503) for a bank whose
        manifest simply has not landed yet — the builder writes the
        manifest LAST, so 'no manifest' means 'still building': wait."""
        from moco_tpu.serve import bankbuild

        try:
            feats, labels, meta = bankbuild.load_bank(bank)
        except (OSError, ValueError, KeyError) as e:
            raise ValueError(f"cannot load bank {bank!r}: {e}") from e
        if meta is None:
            raise ValueError(
                f"bank {bank!r} has no integrity manifest yet — a "
                "versioned bank writes its manifest last, so this build "
                "may still be in flight; retry once it lands"
            )
        bad = bankbuild.verify_bank(meta["bank_dir"], meta["step"])
        if bad is not None:
            raise BankMismatchError(
                f"bank {bank!r} fails its integrity manifest: {bad}"
            )
        from moco_tpu.resilience.integrity import digest_file

        ckpt_sha = digest_file(pretrained)
        if meta.get("checkpoint_sha256") != ckpt_sha:
            raise BankMismatchError(
                f"bank {bank!r} (step {meta['step']}) was built against "
                f"checkpoint sha256 {meta.get('checkpoint_sha256')!r}, "
                f"but {pretrained!r} hashes to {ckpt_sha!r} — not a "
                "pair; build a paired bank with tools/bank_build.py"
            )
        if bank_step is not None and int(bank_step) != meta["step"]:
            raise BankMismatchError(
                f"offered bank_step {bank_step} != bank's recorded step "
                f"{meta['step']}"
            )
        if len(feats) != len(labels) or np.asarray(feats).ndim != 2:
            raise BankMismatchError(
                f"bank {bank!r} arrays are malformed: features "
                f"{np.asarray(feats).shape} vs labels "
                f"{np.asarray(labels).shape}"
            )
        return feats, labels, meta

    def _paired_ann(self, bank: str, bank_feats, bank_labels):
        """Load + vet the ANN index paired with an offered bank (ISSUE
        20). Same taxonomy as the bank itself: no manifest yet -> plain
        ValueError (the builder writes the index after the bank and the
        manifest last — retry once it lands); a present-but-torn or
        mispaired index -> `BankMismatchError` (quarantine the pair)."""
        from moco_tpu.serve import ann as annmod

        try:
            loaded = annmod.load_ann(bank)
        except annmod.AnnIndexError as e:
            raise BankMismatchError(
                f"paired ANN index for bank {bank!r} is bad: {e}"
            ) from e
        if loaded is None:
            raise ValueError(
                f"bank {bank!r} has no ANN index manifest yet — the "
                "index is built after the bank (manifest last), so this "
                "build may still be in flight; retry once it lands"
            )
        arrays, _manifest = loaded
        old = self._ann
        try:
            return annmod.AnnShard(
                bank_feats, bank_labels, arrays,
                shard=old.shard, shards=old.shards, nprobe=old.nprobe,
                rerank=old.rerank, temperature=old.temperature,
                num_classes=self._knn_defaults["num_classes"],
            )
        except (annmod.AnnIndexError, ValueError) as e:
            raise BankMismatchError(
                f"paired ANN index for bank {bank!r} does not fit the "
                f"bank: {e}"
            ) from e

    def _bank_agreement(self, new_engine, meta, feat_dim: int,
                        bank: str) -> float:
        """The space-agreement check: mean row-wise cosine between the
        bank's recorded probe features and the NEW engine's embedding of
        the same seeded probe rows. Raises `BankMismatchError` below the
        configured floor (or when the comparison is impossible)."""
        from moco_tpu.serve import bankbuild

        if meta is None or not (meta.get("probe") or {}).get("features"):
            raise BankMismatchError(
                f"bank {bank!r} records no probe rows — cannot verify "
                "space agreement; rebuild it with tools/bank_build.py"
            )
        if meta.get("feat_dim") not in (None, feat_dim):
            raise BankMismatchError(
                f"bank {bank!r} feat_dim {meta['feat_dim']} != new "
                f"engine feat_dim {feat_dim}"
            )
        cap = new_engine.buckets[-1]  # probe rows are a deterministic
        # prefix of one rng stream, so a ladder smaller than the
        # recorded row count compares a prefix — still sound

        def embed_prefix(batch):
            return new_engine.embed(batch[: min(len(batch), cap)])

        try:
            agreement = bankbuild.probe_agreement(embed_prefix, meta)
        except (ValueError, KeyError) as e:
            raise BankMismatchError(
                f"bank {bank!r} probe rows are unusable: {e}"
            ) from e
        if agreement < self.bank_agreement_min:
            raise BankMismatchError(
                f"bank/encoder space-agreement check failed: mean probe "
                f"cosine {agreement:.4f} < floor "
                f"{self.bank_agreement_min:.4f} — the bank was not "
                f"built by this checkpoint's encoder; quarantine the "
                "pair"
            )
        return agreement

    def _probe_stats(self, new_engine) -> dict | None:
        """Cosine drift + dispersion of a fixed probe batch, new engine
        vs live (ISSUE 13). Returns None when the guard is disabled
        (`reload_probe=0`) or either dimensionality makes the comparison
        meaningless (feat-dim change: drift is undefined, and a dim
        change already implies a deliberate re-deploy).

          probe_drift   1 − mean row-wise cosine(old, new): how far the
                        embedding space moved — recorded for the
                        operator (training between exports MOVES it;
                        drift alone is not a failure)
          probe_spread  1 − ‖mean(new unit rows)‖: 0 when every probe
                        maps to one direction — rank-one collapse as
                        seen from serving. THE quarantine signal.
        """
        if self.reload_probe <= 0:
            return None
        s = new_engine.image_size
        n = min(self.reload_probe, new_engine.buckets[-1])
        if n < 2:
            return None  # one row has spread 0 by construction
        # deterministic probe (seeded ctor: mocolint R9-clean): the same
        # batch across reloads makes drift numbers comparable run-long
        probe = np.random.default_rng(20130613).integers(
            0, 256, size=(n, s, s, 3), dtype=np.uint8
        )
        old = self.engine.embed(probe)
        new = new_engine.embed(probe)
        if old.shape != new.shape:
            return None

        def unit(rows: np.ndarray) -> np.ndarray:
            norms = np.linalg.norm(rows, axis=-1, keepdims=True)
            return rows / np.maximum(norms, 1e-12)

        u_old, u_new = unit(old), unit(new)
        drift = 1.0 - float(np.mean(np.sum(u_old * u_new, axis=-1)))
        spread = 1.0 - float(np.linalg.norm(np.mean(u_new, axis=0)))
        return {"probe_drift": round(drift, 6),
                "probe_spread": round(spread, 6)}

    # -- telemetry -----------------------------------------------------------
    def _note_batch(self, n: int, bucket: int, wait_s: float) -> None:
        self._h_queue_wait.observe(wait_s)
        if self.tracer is not None:
            # one executed batch = one capture-window tick (the serve
            # analogue of a train step); transitions land in events.jsonl
            evt = self.tracer.tick(self.batcher.batches)
            if evt is not None and self.registry is not None:
                self.registry.emit("event", event="trace_capture", **evt)
        if (self.registry is not None
                and self.batcher.batches % self.snapshot_every == 0):
            self.registry.emit("serve", **self.stats())

    def stats(self) -> dict:
        """Cumulative snapshot — the `/stats` payload AND the `kind:
        "serve"` telemetry record (the report reads the LAST one)."""
        b = self.batcher
        with self._lock:
            requests, served = self.requests, self.served
        out = {
            "requests": requests,
            "served": served,
            "shed_overload": b.shed_overload,
            "shed_deadline": b.shed_deadline,
            "batch_errors": b.batch_errors,
            "batches": b.batches,
            "occupancy_mean": round(b.occupancy_mean, 4),
            "queue_depth": b.queue_depth,
            "buckets": list(b.buckets),
            "latency_ms": self._h_latency.percentiles_ms(),
            "queue_wait_ms": self._h_queue_wait.percentiles_ms(),
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self._started, 1),
            # per-tier admission breakdown (ISSUE 20); the flat
            # shed_overload/shed_deadline above stay cross-tier TOTALS
            "tiers": {
                "submitted": dict(b.submitted_by_tier),
                "shed_overload": dict(b.shed_overload_by_tier),
                "shed_deadline": dict(b.shed_deadline_by_tier),
                "queue_depth": b.queue_depth_by_tier,
            },
        }
        if self._ann is not None:
            with self._lock:
                candidate_calls = self.ann_candidate_calls
            out["ann"] = dict(
                self._ann.stats(),
                recall_probe=self._ann_recall,
                candidate_calls=candidate_calls,
            )
        with self._lock:
            if self.reloads:
                out["reloads"] = self.reloads
                out["reload_history"] = list(self._reload_history)
        if self.cache is not None:
            out["cache"] = {
                "hits": self.cache.hits,
                "misses": self.cache.misses,
                "hit_rate": round(self.cache.hit_rate, 4),
                "entries": self.cache.entries,
                "bytes": self.cache.cached_bytes,
            }
        trace = self.trace_state()
        if trace is not None:
            out["trace"] = trace
        if self._knn is not None:
            out["bank"] = self.bank_info()
        return out

    def bank_info(self) -> dict:
        """Which embedding space is this replica answering from? The
        `GET /admin/bank` payload and the `/stats` bank block (ISSUE
        16): bank version (step + manifest hash), the checkpoint it was
        built against, row count, and the last swap generation. A plain
        --knn-bank npz (no manifest) reports only size + generation."""
        with self._lock:
            swaps = self._bank_swaps
        knn, meta = self._knn, self._bank_meta
        out: dict = {"configured": knn is not None}
        if knn is None:
            return out
        out.update({
            "rows": int(len(knn["bank"])),
            "feat_dim": int(knn["bank"].shape[1]),
            "generation": self._engine_gen,
            "swaps": swaps,
        })
        if meta is not None:
            out.update({
                "bank_step": meta.get("step"),
                "manifest_sha256": meta.get("manifest_sha256"),
                "checkpoint_sha256": meta.get("checkpoint_sha256"),
                "path": meta.get("path"),
            })
        return out

    def trace_state(self) -> dict | None:
        """Capture-window state for /healthz and /stats ("currently
        profiling?" without reading events.jsonl); None when untraced."""
        return self.tracer.capture_state() if self.tracer is not None else None

    # -- shutdown ------------------------------------------------------------
    def drain(self, timeout_s: float = 60.0) -> bool:
        """Reject new work, complete everything accepted, flush the final
        telemetry snapshot. Idempotent. Returns False when in-flight work
        outlived `timeout_s` (the batcher is then closed non-draining and
        leftovers get a structured rejection — never a silent drop)."""
        self.draining = True
        completed = self.batcher.drain(timeout_s)
        if not completed:
            log_event(
                "serve",
                f"drain timed out after {timeout_s:.0f}s; rejecting the "
                "remainder with structured errors",
            )
        self.batcher.close(drain=False)
        if self.registry is not None:
            self.registry.emit("serve", final=True, **self.stats())
            self.registry.flush()
        if self.tracer is not None:
            self.tracer.flush()  # land any buffered spans with the drain
        return completed
