"""Request-path orchestration: admission → cache → batcher → engine
(ISSUE 5 tentpole).

`EmbedService` is the front end's single entry point. One `embed()` call
walks: shape/dtype validation, the content-hash embedding LRU, the
micro-batcher's bounded admission queue, a bucketed device call, and the
telemetry instruments — returning a feature row or raising one of the
structured rejections from serve/batcher.py. `classify()` rides the same
path and finishes with a weighted-kNN vote against a precomputed feature
bank (`ops/knn.knn_predict`, the InstDisc protocol the pretrain monitor
uses).

Telemetry: latency / batch-occupancy / queue-wait histograms feed
cumulative `kind: "serve"` snapshot records into the SAME events.jsonl
stream training writes (`MetricsRegistry`), emitted every
`snapshot_every` batches and once at drain — `tools/telemetry_report.py`
renders the last snapshot as its `serve:` section.

Shutdown: `drain()` (SIGTERM in tools/serve.py) stops admission, lets
every accepted request finish, and flushes the final snapshot — reject
new, complete old, then exit."""

from __future__ import annotations

import threading
import time

import numpy as np

from moco_tpu.serve.batcher import MicroBatcher
from moco_tpu.serve.cache import EmbeddingCache
from moco_tpu.telemetry.registry import Histogram
from moco_tpu.utils.logging import log_event

# most-recent observations the stats histograms keep: a server runs for
# weeks — unbounded reservoirs (fine for a bounded training run) would
# grow memory and per-snapshot sort cost forever, and an operator wants
# RECENT percentiles from /stats anyway
STATS_WINDOW = 8192


class EmbedService:
    def __init__(
        self,
        engine,
        *,
        flush_ms: float = 10.0,
        max_queue: int = 256,
        request_deadline_ms: float = 2000.0,
        cache_mb: int = 0,
        registry=None,
        snapshot_every: int = 25,
        tracer=None,
        shed_spike_min: int = 8,
        knn_bank: np.ndarray | None = None,
        knn_labels: np.ndarray | None = None,
        num_classes: int = 0,
        knn_k: int = 200,
        knn_temperature: float = 0.07,
    ):
        self.engine = engine
        self.feat_dim = engine.warmup()  # every bucket compiled before traffic
        self.cache = EmbeddingCache(cache_mb) if cache_mb else None
        self.registry = registry
        self.snapshot_every = max(int(snapshot_every), 1)
        self.draining = False
        self._lock = threading.Lock()
        self.requests = 0
        self.served = 0
        self._started = time.monotonic()  # uptime is a duration, not a timestamp
        self._h_latency = Histogram("serve_latency_s", window=STATS_WINDOW)
        self._h_queue_wait = Histogram("serve_queue_wait_s",
                                       window=STATS_WINDOW)
        self._request_deadline_s = float(request_deadline_ms) / 1e3
        # tracing (ISSUE 8): the batcher stamps request/flush/engine spans
        # and arms shed-spike captures; the service ticks the capture
        # window once per executed batch and surfaces the capture state on
        # /healthz + /stats
        self.tracer = tracer
        self.batcher = MicroBatcher(
            engine.embed,
            buckets=engine.buckets,
            flush_ms=flush_ms,
            max_queue=max_queue,
            default_deadline_ms=request_deadline_ms,
            on_batch=self._note_batch,
            tracer=tracer,
            shed_spike_min=shed_spike_min,
        )
        self._knn = None
        if knn_bank is not None:
            if knn_labels is None or len(knn_bank) != len(knn_labels):
                raise ValueError("knn_bank needs matching knn_labels")
            labels = np.asarray(knn_labels, np.int32)
            self._knn = {
                "bank": np.asarray(knn_bank, np.float32),
                "labels": labels,
                "num_classes": int(num_classes or labels.max() + 1),
                "k": int(knn_k),
                "temperature": float(knn_temperature),
            }
            # pre-compile the kNN program too: the first classify must not
            # pay a trace under live traffic (same rule as engine.warmup)
            self._knn_predict(np.ones((1, self.feat_dim), np.float32))
        if self.registry is not None:
            self.registry.emit(
                "serve_start",
                image_size=engine.image_size,
                feat_dim=self.feat_dim,
                buckets=list(engine.buckets),
                flush_ms=flush_ms,
                max_queue=max_queue,
                request_deadline_ms=request_deadline_ms,
                cache_mb=cache_mb,
                knn_bank_size=0 if self._knn is None else len(self._knn["bank"]),
            )

    # -- request paths -------------------------------------------------------
    def embed(self, image: np.ndarray,
              deadline_s: float | None = None) -> tuple[np.ndarray, bool]:
        """One request: returns `(embedding, cache_hit)` or raises a
        `RejectionError` subclass (overloaded / deadline_exceeded /
        draining) — the caller always gets a decision."""
        image = self._validate(image)
        with self._lock:
            self.requests += 1
        t0 = time.monotonic()
        key = None
        if self.cache is not None:
            key = EmbeddingCache.key_for(image)
            hit = self.cache.get(key)
            if hit is not None:
                with self._lock:
                    self.served += 1
                self._h_latency.observe(time.monotonic() - t0)
                return hit, True
        pending = self.batcher.submit(image, deadline_s)
        # generous slack over the request deadline: the batcher ALWAYS
        # resolves accepted requests, so this only guards a dead flusher
        result = pending.wait(
            timeout=(deadline_s or self._request_deadline_s) + 30.0
        )
        self._h_latency.observe(time.monotonic() - t0)
        if self.cache is not None:
            self.cache.put(key, result)
        with self._lock:
            self.served += 1
        return result, False

    def classify(self, image: np.ndarray,
                 deadline_s: float | None = None) -> tuple[int, np.ndarray, bool]:
        """kNN-classify against the precomputed feature bank: returns
        `(class_id, embedding, cache_hit)`."""
        if self._knn is None:
            raise ValueError(
                "no kNN feature bank configured (serve with --knn-bank)"
            )
        embedding, cached = self.embed(image, deadline_s)
        pred = self._knn_predict(embedding[None, :])
        return int(pred[0]), embedding, cached

    def _knn_predict(self, features: np.ndarray) -> np.ndarray:
        from moco_tpu.ops.knn import knn_predict

        k = self._knn
        return np.asarray(knn_predict(
            features, k["bank"], k["labels"], k["num_classes"],
            k=k["k"], temperature=k["temperature"],
        ))

    def _validate(self, image) -> np.ndarray:
        image = np.asarray(image)
        s = self.engine.image_size
        if image.shape != (s, s, 3) or image.dtype != np.uint8:
            raise ValueError(
                f"expected one [{s}, {s}, 3] uint8 image, got "
                f"{image.shape} {image.dtype}"
            )
        return image

    # -- telemetry -----------------------------------------------------------
    def _note_batch(self, n: int, bucket: int, wait_s: float) -> None:
        self._h_queue_wait.observe(wait_s)
        if self.tracer is not None:
            # one executed batch = one capture-window tick (the serve
            # analogue of a train step); transitions land in events.jsonl
            evt = self.tracer.tick(self.batcher.batches)
            if evt is not None and self.registry is not None:
                self.registry.emit("event", event="trace_capture", **evt)
        if (self.registry is not None
                and self.batcher.batches % self.snapshot_every == 0):
            self.registry.emit("serve", **self.stats())

    def stats(self) -> dict:
        """Cumulative snapshot — the `/stats` payload AND the `kind:
        "serve"` telemetry record (the report reads the LAST one)."""
        b = self.batcher
        with self._lock:
            requests, served = self.requests, self.served
        out = {
            "requests": requests,
            "served": served,
            "shed_overload": b.shed_overload,
            "shed_deadline": b.shed_deadline,
            "batch_errors": b.batch_errors,
            "batches": b.batches,
            "occupancy_mean": round(b.occupancy_mean, 4),
            "queue_depth": b.queue_depth,
            "buckets": list(b.buckets),
            "latency_ms": self._h_latency.percentiles_ms(),
            "queue_wait_ms": self._h_queue_wait.percentiles_ms(),
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self._started, 1),
        }
        if self.cache is not None:
            out["cache"] = {
                "hits": self.cache.hits,
                "misses": self.cache.misses,
                "hit_rate": round(self.cache.hit_rate, 4),
                "entries": self.cache.entries,
                "bytes": self.cache.cached_bytes,
            }
        trace = self.trace_state()
        if trace is not None:
            out["trace"] = trace
        return out

    def trace_state(self) -> dict | None:
        """Capture-window state for /healthz and /stats ("currently
        profiling?" without reading events.jsonl); None when untraced."""
        return self.tracer.capture_state() if self.tracer is not None else None

    # -- shutdown ------------------------------------------------------------
    def drain(self, timeout_s: float = 60.0) -> bool:
        """Reject new work, complete everything accepted, flush the final
        telemetry snapshot. Idempotent. Returns False when in-flight work
        outlived `timeout_s` (the batcher is then closed non-draining and
        leftovers get a structured rejection — never a silent drop)."""
        self.draining = True
        completed = self.batcher.drain(timeout_s)
        if not completed:
            log_event(
                "serve",
                f"drain timed out after {timeout_s:.0f}s; rejecting the "
                "remainder with structured errors",
            )
        self.batcher.close(drain=False)
        if self.registry is not None:
            self.registry.emit("serve", final=True, **self.stats())
            self.registry.flush()
        if self.tracer is not None:
            self.tracer.flush()  # land any buffered spans with the drain
        return completed
