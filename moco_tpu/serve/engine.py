"""Bucketed-compile embedding engine (ISSUE 5 tentpole).

XLA compiles one program per input SHAPE: a naive server jitting whatever
batch size the batcher produced would recompile on nearly every distinct
coalesce (1, 3, 7, 12, ...) — each a multi-second stall under load. The
engine instead pads every batch to a small fixed ladder of bucket shapes
(default 1/8/32/128), pre-compiles ALL of them at `warmup()`, and then
never compiles again: steady-state load sees only warm program launches.

Soundness of padding (test-pinned): with `train=False` the encoder runs
BN on running stats, so every per-row computation is independent of batch
composition — the same image embeds BIT-IDENTICALLY whether it rides
solo in the 1-bucket or padded among strangers in the 128-bucket, and
identically to a direct `model.apply` on the same normalized input.

Preprocessing matches the eval path (data/augment.py): uint8 canvases at
the model resolution are scaled to [0,1] and normalized with the
ImageNet mean/std — the transform every frozen-feature consumer
(lincls, kNN) applies after its deterministic center crop. Cropping and
resizing stay client-side: the service's contract is "model-resolution
RGB in, feature vector out".
"""

from __future__ import annotations

import numpy as np

from moco_tpu.serve.batcher import bucket_for, validate_buckets

DEFAULT_BUCKETS = (1, 8, 32, 128)


class EmbeddingEngine:
    """Jitted feature extraction over a fixed bucket ladder.

    `embed(images_u8)` accepts `[n, S, S, 3]` uint8 with any
    `1 <= n <= buckets[-1]`, pads to the smallest fitting bucket, and
    returns the first `n` feature rows as float32 numpy. Call `warmup()`
    (the service does) before taking traffic so every bucket's program is
    already compiled."""

    def __init__(self, model, params, batch_stats, *, image_size: int,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        import jax
        import jax.numpy as jnp

        from moco_tpu.data.augment import IMAGENET_INV_STD, IMAGENET_MEAN

        self.model = model
        self.image_size = int(image_size)
        self.buckets = validate_buckets(buckets)
        # pin the frozen weights to a device ONCE — uncommitted host
        # arrays would be re-placed on every call (the lincls lesson)
        self.params = jax.device_put(params)
        self.batch_stats = jax.device_put(batch_stats or {})
        self.feat_dim: int | None = None
        mean = jnp.asarray(IMAGENET_MEAN)
        inv_std = jnp.asarray(IMAGENET_INV_STD)

        def _apply(p, stats, images_u8):
            x = images_u8.astype(jnp.float32) / 255.0
            x = (x - mean) * inv_std
            return model.apply({"params": p, "batch_stats": stats}, x,
                               train=False)

        self._jitted = jax.jit(_apply)

    @classmethod
    def from_checkpoint(cls, path: str, arch: str, *, image_size: int = 224,
                        cifar_stem: bool = False,
                        buckets: tuple[int, ...] = DEFAULT_BUCKETS
                        ) -> "EmbeddingEngine":
        """Load a pretraining export through the shared checkpoint-surgery
        loader (`checkpoint.load_for_inference` — the same dialect table
        lincls and the Detectron2 converter consume). Imported lazily:
        the serve package stays import-light for callers that bring their
        own params (bench, tests)."""
        from moco_tpu.checkpoint import load_for_inference

        model, params, stats = load_for_inference(
            path, arch, image_size=image_size, cifar_stem=cifar_stem
        )
        return cls(model, params, stats, image_size=image_size,
                   buckets=buckets)

    # -- lifecycle -----------------------------------------------------------
    def warmup(self) -> int:
        """Compile every bucket's program up front (zeros batches) so no
        live request ever pays a compile. Returns the feature dim."""
        s = self.image_size
        for b in self.buckets:
            out = self._jitted(
                self.params, self.batch_stats,
                np.zeros((b, s, s, 3), np.uint8),
            )
        self.feat_dim = int(out.shape[-1])
        return self.feat_dim

    def compiled_programs(self) -> int | None:
        """How many distinct programs the jit cache holds (None when this
        jax build doesn't expose the introspection). After `warmup()` this
        must STAY at `len(buckets)` under any load — the no-recompile
        guarantee the tests pin."""
        try:
            return int(self._jitted._cache_size())
        except (AttributeError, TypeError):
            return None

    # -- the hot path --------------------------------------------------------
    def embed(self, images_u8: np.ndarray) -> np.ndarray:
        images_u8 = np.asarray(images_u8)
        s = self.image_size
        if (images_u8.ndim != 4 or images_u8.shape[1:] != (s, s, 3)
                or images_u8.dtype != np.uint8):
            raise ValueError(
                f"expected [n, {s}, {s}, 3] uint8, got "
                f"{images_u8.shape} {images_u8.dtype}"
            )
        n = images_u8.shape[0]
        bucket = bucket_for(n, self.buckets)  # raises when n > buckets[-1]
        if n < bucket:
            padded = np.zeros((bucket, s, s, 3), np.uint8)
            padded[:n] = images_u8
        else:
            padded = images_u8
        out = self._jitted(self.params, self.batch_stats, padded)
        return np.asarray(out[:n], np.float32)
