"""stdlib HTTP front end over `EmbedService` (ISSUE 5 tentpole).

One thread per connection (`ThreadingHTTPServer`): each request blocks in
`service.embed` until its coalesced batch resolves, which is exactly the
concurrency shape the micro-batcher feeds on — N in-flight HTTP requests
ARE the batch. No web framework: the container bakes no server deps, and
the protocol is four routes of JSON.

    POST /v1/embed   {"image_b64": <raw uint8 RGB bytes>, "shape": [S,S,3]}
                     (or {"pixels": nested list}; optional "deadline_ms",
                     optional "tier": "interactive"|"batch" — the
                     admission lane, ISSUE 20)
                 →   200 {"embedding": [...], "cached": bool}
    POST /v1/knn     same body → 200 {"class": int, "cached": bool}
                     (+"embedding" when "return_embedding" is true).
                     With {"candidates": true, "embedding": [...]} —
                     the fleet router's ANN fan-out leg — answers this
                     replica's shard-local candidates instead:
                     {"candidates": [[sim, label], ...], "temperature",
                     "k", "num_classes", "shard", "shards"}
    POST /admin/reload  {"pretrained": <path>, "step": <int>?,
                     "bank": <path>?, "bank_step": <int>?} → hot weight
                     reload (ISSUE 10): build + warm a new engine
                     off-path, atomically swap between micro-batches.
                     With "bank", the dual swap (ISSUE 16): engine +
                     kNN bank roll together under one generation bump.
                     200 on swap; 409 {"error": "reload_refused"} when
                     this process's config can never accept it (bank
                     configured but no pair offered — body carries
                     "bank_step", the serving bank's recorded step —
                     image_size/ladder change; terminal, the fleet
                     stops retrying); 409 {"error":
                     "reload_bank_mismatch"} when the offered
                     (checkpoint, bank) pair fails verification — the
                     fleet quarantines the pair and rolls back; 503
                     {"error": "reload_failed"} when the checkpoint
                     couldn't be loaded/warmed (possibly transient —
                     retried). Old weights keep serving on every
                     failure. OPERATOR-ONLY: the fleet router never
                     proxies /admin/* — only the fleet supervisor (or an
                     operator on the replica's own port) reaches it.
    GET  /admin/bank 200 <service.bank_info()> — which embedding space
                     this replica answers from (ISSUE 16)
    GET  /healthz    200 {"status": "ok"} | 503 {"status": "draining"}
    GET  /stats      200 <service.stats()>

Rejections are STRUCTURED, never hangs: the batcher's typed errors map to
HTTP statuses with a machine-readable body — 503 `{"error":
"overloaded", "retry_after_ms": ...}`, 504 `{"error":
"deadline_exceeded"}`, 503 `{"error": "draining"}` — so a load balancer
or client can distinguish shed from broken."""

from __future__ import annotations

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from moco_tpu.serve.batcher import RejectionError
from moco_tpu.serve.service import (
    BankMismatchError,
    CollapsedCheckpointError,
    ReloadRefusedError,
)


def decode_image(req: dict) -> np.ndarray:
    """Request body → one uint8 image array; ValueError on any malformed
    input (the front end maps it to 400, never a traceback)."""
    if "image_b64" in req:
        shape = req.get("shape")
        if (not isinstance(shape, (list, tuple)) or len(shape) != 3):
            raise ValueError('image_b64 needs "shape": [h, w, 3]')
        try:
            buf = base64.b64decode(req["image_b64"], validate=True)
        except (ValueError, TypeError) as e:
            raise ValueError(f"image_b64 is not valid base64: {e}")
        arr = np.frombuffer(buf, np.uint8)
        expected = int(np.prod([int(s) for s in shape]))
        if arr.size != expected:
            raise ValueError(
                f"image_b64 carries {arr.size} bytes, shape {shape} "
                f"needs {expected}"
            )
        return arr.reshape([int(s) for s in shape])
    if "pixels" in req:
        try:
            return np.asarray(req["pixels"], np.uint8)
        except (ValueError, TypeError) as e:
            raise ValueError(f"pixels is not a uint8 image array: {e}")
    raise ValueError('body needs "image_b64"+"shape" or "pixels"')


def _make_handler(service):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: closed-loop clients (serve_bench) reuse connections
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            # per-request stderr lines drown real events under load; the
            # structured channel is service.stats()/telemetry
            pass

        def _send(self, status: int, obj: dict) -> None:
            body = json.dumps(obj).encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _maybe_wedge(self) -> None:
            """Chaos `wedge_at_request` (ISSUE 10 fleet drill): once the
            service is wedged, EVERY route — /healthz included — accepts
            the connection and then never answers. From outside this is
            exactly a stuck event loop / dead device: the fleet
            supervisor's probe-staleness kill is the only way out."""
            while service.wedged:
                time.sleep(3600.0)

        def do_GET(self):
            self._maybe_wedge()
            if self.path == "/healthz":
                # trace state (ISSUE 8 satellite): a balancer/operator sees
                # "currently profiling" straight from the health probe.
                # `draining` read ONCE: a drain flipping between body and
                # status would send a 503 whose body still says ok
                draining = service.draining
                trace = getattr(service, "trace_state", lambda: None)()
                if draining:
                    body = {"status": "draining"}
                else:
                    body = {"status": "ok",
                            "queue_depth": service.batcher.queue_depth}
                if trace is not None:
                    body["trace"] = trace
                self._send(503 if draining else 200, body)
            elif self.path == "/stats":
                self._send(200, service.stats())
            elif self.path == "/admin/bank":
                self._send(200, service.bank_info())
            else:
                self._send(404, {"error": "not_found", "path": self.path})

        def do_POST(self):
            self._maybe_wedge()
            if self.path == "/admin/reload":
                self._admin_reload()
                return
            if self.path not in ("/v1/embed", "/v1/knn"):
                # body must still be consumed on HTTP/1.1 keep-alive
                self.rfile.read(int(self.headers.get("Content-Length") or 0))
                self._send(404, {"error": "not_found", "path": self.path})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError("body must be a JSON object")
                deadline_ms = req.get("deadline_ms")
                deadline_s = (
                    float(deadline_ms) / 1e3 if deadline_ms else None
                )
                tier = req.get("tier", "interactive")
                if tier not in ("interactive", "batch"):
                    raise ValueError(
                        f'unknown tier {tier!r} ("interactive" or "batch")'
                    )
                # ANN candidate probe (ISSUE 20): the fleet router's
                # fan-out leg carries an EMBEDDING, not an image — no
                # batcher, no device call, pure index search
                candidates = (self.path == "/v1/knn"
                              and req.get("candidates"))
                image = None if candidates else decode_image(req)
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": "bad_request", "detail": str(e)})
                return
            try:
                if candidates:
                    emb = req.get("embedding")
                    if not isinstance(emb, list) or not emb:
                        raise ValueError(
                            'candidates mode needs "embedding": [...]'
                        )
                    self._send(200, service.ann_candidates(emb))
                    return
                if self.path == "/v1/knn":
                    cls_id, embedding, cached = service.classify(
                        image, deadline_s, tier=tier
                    )
                    resp = {"class": cls_id, "cached": cached}
                    if req.get("return_embedding"):
                        resp["embedding"] = [float(v) for v in embedding]
                else:
                    embedding, cached = service.embed(image, deadline_s,
                                                      tier=tier)
                    resp = {"embedding": [float(v) for v in embedding],
                            "cached": cached}
                self._send(200, resp)
            except RejectionError as e:
                self._send(e.http_status,
                           {"error": e.code, "detail": str(e), **e.fields})
            except ValueError as e:  # e.g. wrong resolution for this model
                self._send(400, {"error": "bad_request", "detail": str(e)})
            except Exception as e:  # a handler crash must answer, not hang
                self._send(500, {"error": "internal", "detail": repr(e)})

        def _admin_reload(self):
            """Hot weight reload (ISSUE 10). Failures answer 409 with the
            reason — the old weights keep serving either way, and the
            caller (the fleet supervisor's reload roll) distinguishes a
            bad checkpoint from a dead replica by the structured body."""
            try:
                length = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict) or not req.get("pretrained"):
                    raise ValueError('body needs {"pretrained": <path>}')
                step = req.get("step")
                step = int(step) if step is not None else None
                bank = req.get("bank")
                bank = str(bank) if bank else None
                bank_step = req.get("bank_step")
                bank_step = int(bank_step) if bank_step is not None else None
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                # a malformed REQUEST (non-integer step included) is the
                # client's bug, not a checkpoint failure: 400, not 409
                self._send(400, {"error": "bad_request", "detail": str(e)})
                return
            if service.draining:
                self._send(503, {"error": "draining"})
                return
            try:
                entry = service.reload(str(req["pretrained"]), step,
                                       bank=bank, bank_step=bank_step)
                self._send(200, {"status": "reloaded", **entry})
            except BankMismatchError as e:
                # dual swap (ISSUE 16): the offered (checkpoint, bank)
                # PAIR is bad — its own code so the fleet quarantines
                # the pair as a unit and rolls back half-swapped
                # replicas (checked before ReloadRefusedError: it IS one)
                self._send(409, {"error": "reload_bank_mismatch",
                                 "detail": str(e)})
            except CollapsedCheckpointError as e:
                # drift guard (ISSUE 13): the CHECKPOINT is bad, not this
                # process's config — its own error code so the fleet
                # quarantines the step instead of merely not retrying
                self._send(409, {"error": "reload_collapsed",
                                 "detail": str(e)})
            except ReloadRefusedError as e:
                # TERMINAL for this process config (bank without a pair,
                # image_size, ladder): 409 — the fleet stops retrying
                # this step here. Under a configured versioned bank the
                # body names the bank's recorded checkpoint step so the
                # operator sees WHICH pair is missing its other half.
                body = {"error": "reload_refused", "detail": str(e)}
                if getattr(e, "bank_step", None) is not None:
                    body["bank_step"] = e.bank_step
                self._send(409, body)
            except ValueError as e:
                # load/warmup failure: possibly transient (NFS blip, a
                # momentary OOM) — 503 so the fleet's converge loop
                # retries on its next pass
                self._send(503, {"error": "reload_failed", "detail": str(e)})
            except Exception as e:  # must answer, never hang the roll
                self._send(503, {"error": "reload_failed", "detail": repr(e)})

    return Handler


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # listen backlog: socketserver's default of 5 resets connections the
    # moment a few dozen closed-loop clients reconnect at once (urllib
    # opens a fresh TCP connection per request) — the admission queue, not
    # the kernel backlog, is where this service sheds load
    request_queue_size = 128


class ServeFrontend:
    """Owns the `ThreadingHTTPServer`; `port=0` binds an ephemeral port
    (tests, in-process bench) and exposes the real one as `.port`."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        self.server = _Server((host, port), _make_handler(service))
        self.host, self.port = self.server.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True, name="serve-http"
        )
        self._thread.start()

    def shutdown(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
