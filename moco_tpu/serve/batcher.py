"""Dynamic micro-batcher with admission control (ISSUE 5 tentpole).

Online serving inverts pretraining's batching problem: requests arrive one
at a time, but the accelerator amortizes fixed per-call cost only over
LARGE calls. The batcher coalesces concurrent requests into few device
calls — flush on max-batch-size OR deadline, whichever comes first — the
same amortize-without-unbounded-latency tradeoff FAST (PAPERS.md) makes
for all-to-all scheduling.

Contracts the tests pin:

  - FIFO: requests are batched strictly in arrival order; a deadline
    flush takes the OLDEST prefix of the queue.
  - shed, never stall: the admission queue has a bounded depth — at
    capacity `submit` raises `OverloadedError` immediately (the caller
    gets a structured rejection with a retry hint, not unbounded
    latency). A request whose own deadline passed while it sat queued is
    resolved with `DeadlineExceededError` instead of wasting a device
    slot on an answer nobody is waiting for.
  - drain, never drop: `drain()` stops admission and flushes EVERYTHING
    already accepted — every in-flight request completes (SIGTERM
    semantics; tools/serve.py wires it through the
    resilience/preemption.py handler-chaining pattern).

The batcher never touches jax: `run_batch` is any `[n, ...] -> [n, D]`
callable (serve/engine.py's bucketed-compile `embed` in production, a
stub in the unit tests), so batching semantics are testable without a
compile in sight.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from moco_tpu.telemetry.trace import SpikeDetector, null_tracer

# Admission tiers (ISSUE 20): interactive user traffic and bulk batch
# work (bank_build re-embeds) ride SEPARATE bounded queues with separate
# deadlines, so a batch flood can fill its own lane to the brim without
# ever costing an interactive request its admission slot. The flusher
# serves interactive strictly first and backfills spare bucket capacity
# with batch rows — priority, not partitioned throughput.
TIERS = ("interactive", "batch")


class RejectionError(Exception):
    """A request that got a structured DECISION instead of a result.

    `code` is the wire-visible discriminator (the HTTP front end maps it
    to a status + JSON error body); `fields` carry machine-readable
    context (e.g. `retry_after_ms`)."""

    code = "rejected"
    http_status = 503

    def __init__(self, msg: str, **fields):
        super().__init__(msg)
        self.fields = fields


class OverloadedError(RejectionError):
    """Admission queue at capacity — shed at the door, retry later."""

    code = "overloaded"
    http_status = 503


class DeadlineExceededError(RejectionError):
    """The request's own deadline passed before a device slot reached it."""

    code = "deadline_exceeded"
    http_status = 504


class DrainingError(RejectionError):
    """The service is shutting down; new work is rejected, in-flight
    work completes."""

    code = "draining"
    http_status = 503


def bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    """Smallest padded bucket shape that fits `n` requests. `buckets` is
    ascending; `n` must fit the largest (the batcher never pops more)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket {buckets[-1]}")


def validate_buckets(buckets) -> tuple[int, ...]:
    b = tuple(int(x) for x in buckets)
    if not b or any(x < 1 for x in b) or list(b) != sorted(set(b)):
        raise ValueError(
            f"buckets must be ascending unique positive sizes, got {buckets!r}"
        )
    return b


class PendingRequest:
    """One queued request: payload in, exactly-one-of (result, error) out.
    `enqueue_wall` is the wall-clock twin of the monotonic `enqueue_t` —
    the trace layer records the request's admission→resolve span
    retroactively at resolve time (ISSUE 8), and cross-process timelines
    merge on wall-clock."""

    __slots__ = ("payload", "enqueue_t", "enqueue_wall", "deadline_t",
                 "tier", "result", "error", "_done")

    def __init__(self, payload, enqueue_t: float, deadline_t: float,
                 tier: str = "interactive"):
        self.payload = payload
        self.enqueue_t = enqueue_t
        self.tier = tier
        # wall-clock by design: retroactive request spans must merge
        # with other processes' timelines on a shared clock; the value
        # never feeds computation  # mocolint: disable=R9
        self.enqueue_wall = time.time()
        self.deadline_t = deadline_t
        self.result = None
        self.error: Exception | None = None
        self._done = threading.Event()

    def resolve(self, result=None, error: Exception | None = None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: float | None = None):
        """Block for the batcher's decision; raises the structured error
        for shed/failed requests. The batcher resolves every accepted
        request (execute, shed, or drain-reject), so a timeout here means
        the flusher thread itself died — surfaced as a hard error, never
        a silent None."""
        if not self._done.wait(timeout):
            raise RuntimeError(
                "batcher never resolved the request (flusher thread dead?)"
            )
        if self.error is not None:
            raise self.error
        return self.result


class MicroBatcher:
    """Deadline-or-size flushing over a bounded FIFO admission queue.

    `run_batch([n, ...]) -> [n, D]` executes one coalesced batch (n is
    ≤ `buckets[-1]`; padding to the bucket shape is the executor's
    concern — see serve/engine.py). `on_batch(n, bucket, wait_s)` fires
    after each executed batch with the real occupancy numerator, the
    padded bucket, and the oldest request's queue wait.
    """

    def __init__(
        self,
        run_batch,
        *,
        buckets: tuple[int, ...] = (1, 8, 32, 128),
        flush_ms: float = 10.0,
        max_queue: int = 256,
        default_deadline_ms: float = 2000.0,
        on_batch=None,
        name: str = "embed",
        tracer=None,
        shed_spike_min: int = 8,
        batch_max_queue: int | None = None,
        batch_deadline_ms: float | None = None,
    ):
        self.buckets = validate_buckets(buckets)
        if max_queue < self.buckets[-1]:
            raise ValueError(
                f"max_queue ({max_queue}) must hold at least one full "
                f"bucket ({self.buckets[-1]}) or the largest bucket can "
                "never fill"
            )
        self._run_batch = run_batch
        self._flush_s = float(flush_ms) / 1e3
        self.max_queue = int(max_queue)
        self._default_deadline_s = float(default_deadline_ms) / 1e3
        # batch lane defaults: same depth as interactive, a LONGER
        # deadline (bulk work tolerates queueing; it must not be shed by
        # a deadline tuned for user latency)
        self.max_queue_by_tier = {
            "interactive": int(max_queue),
            "batch": int(batch_max_queue if batch_max_queue is not None
                         else max_queue),
        }
        self._deadline_s_by_tier = {
            "interactive": self._default_deadline_s,
            "batch": (float(batch_deadline_ms) / 1e3
                      if batch_deadline_ms is not None
                      else self._default_deadline_s),
        }
        self._on_batch = on_batch
        # tracing (ISSUE 8): flush/engine spans + retroactive per-request
        # spans, and the shed-spike detector arming a budgeted capture
        # window. The null tracer keeps the request path branch-free.
        self._tracer = tracer if tracer is not None else null_tracer()
        self._shed_spike = SpikeDetector(min_events=shed_spike_min)
        self._flush_seq = 0
        self._queues: dict[str, deque[PendingRequest]] = {
            t: deque() for t in TIERS
        }
        self._cond = threading.Condition()
        self._draining = False
        self._closed = False
        self._inflight = 0
        # counters (read under the cond lock by stats consumers).
        # shed_overload/shed_deadline stay TOTALS across tiers (the
        # pre-tier stats contract); *_by_tier carry the breakdown.
        self.submitted = 0
        self.completed = 0
        self.shed_overload = 0
        self.shed_deadline = 0
        self.batch_errors = 0
        self.batches = 0
        self.occupancy_sum = 0.0
        self.submitted_by_tier = {t: 0 for t in TIERS}
        self.shed_overload_by_tier = {t: 0 for t in TIERS}
        self.shed_deadline_by_tier = {t: 0 for t in TIERS}
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True, name=f"{name}-flusher"
        )
        self._thread.start()

    # -- admission -----------------------------------------------------------
    def submit(self, payload, deadline_s: float | None = None,
               tier: str = "interactive") -> PendingRequest:
        """Admit one request or raise a structured rejection IMMEDIATELY
        (bounded queue: the overloaded answer must be cheap and instant,
        never a timeout the client discovers on their own). Admission is
        PER TIER: a full batch lane sheds batch work only."""
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r} (one of {TIERS})")
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self._deadline_s_by_tier[tier]
        pending = PendingRequest(payload, now, now + deadline_s, tier)
        queue_len = -1
        with self._cond:
            if self._draining or self._closed:
                raise DrainingError("service is draining; not accepting work")
            q = self._queues[tier]
            if len(q) >= self.max_queue_by_tier[tier]:
                self.shed_overload += 1
                self.shed_overload_by_tier[tier] += 1
                queue_len = len(q)
            else:
                self.submitted += 1
                self.submitted_by_tier[tier] += 1
                q.append(pending)
                self._cond.notify_all()
        if queue_len >= 0:
            # tracer work OUTSIDE the admission lock: a span-ring flush is
            # a file write, and an overload storm is exactly when the lock
            # must stay cheap — "shed, never stall" includes not stalling
            # the OTHER submitters on shed bookkeeping
            if self._shed_spike.note():
                # a shed SPIKE (vs a lone shed) is the moment worth a
                # profile: arm the capture window, budget-bounded
                self._tracer.maybe_autocapture("shed_spike")
            self._tracer.instant("shed_overload", cat="serve",
                                 queue=queue_len, tier=tier)
            # crude but honest hint: full queues ahead of this request
            # each take at least one flush window to clear
            depth_batches = 1 + queue_len // self.buckets[-1]
            raise OverloadedError(
                f"admission queue full "
                f"({self.max_queue_by_tier[tier]}, tier={tier})",
                retry_after_ms=round(depth_batches * self._flush_s * 1e3, 1),
                tier=tier,
            )
        return pending

    def _qlen(self) -> int:
        # caller holds self._cond
        return sum(len(q) for q in self._queues.values())

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return self._qlen() + self._inflight

    @property
    def queue_depth_by_tier(self) -> dict:
        with self._cond:
            return {t: len(q) for t, q in self._queues.items()}

    @property
    def occupancy_mean(self) -> float:
        with self._cond:
            return self.occupancy_sum / self.batches if self.batches else 0.0

    # -- the flusher ---------------------------------------------------------
    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while not self._qlen() and not self._closed:
                    self._cond.wait()
                if not self._qlen():  # closed and empty: done
                    return
                # coalesce window: more work may arrive until the oldest
                # request's flush deadline OR a full largest bucket,
                # whichever first; draining flushes immediately
                flush_at = min(
                    q[0].enqueue_t for q in self._queues.values() if q
                ) + self._flush_s
                while (self._qlen() < self.buckets[-1]
                       and not self._draining and not self._closed):
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                # interactive first, batch backfills spare bucket slots
                take = min(self._qlen(), self.buckets[-1])
                batch = []
                for tier in TIERS:
                    q = self._queues[tier]
                    while q and len(batch) < take:
                        batch.append(q.popleft())
                self._inflight = len(batch)
            try:
                self._execute(batch)
            finally:
                with self._cond:
                    self._inflight = 0
                    self._cond.notify_all()

    def _execute(self, batch: list[PendingRequest]) -> None:
        now = time.monotonic()
        self._flush_seq += 1
        seq = self._flush_seq  # joins request spans to their flush span
        live, expired = [], []
        for p in batch:
            (live if p.deadline_t > now else expired).append(p)
        for p in expired:
            p.resolve(error=DeadlineExceededError(
                f"deadline passed after {now - p.enqueue_t:.3f}s in queue",
                queued_ms=round((now - p.enqueue_t) * 1e3, 1),
            ))
            self._request_span(p, now, "deadline_exceeded", seq)
        with self._cond:
            self.shed_deadline += len(expired)
            for p in expired:
                self.shed_deadline_by_tier[p.tier] += 1
        if not live:
            return
        bucket = bucket_for(len(live), self.buckets)
        with self._tracer.span("flush_batch", cat="serve", n=len(live),
                               bucket=bucket, seq=seq):
            try:
                with self._tracer.span("engine", cat="serve", detail=True,
                                       bucket=bucket):
                    # asANYarray: the service tags rows with the engine
                    # generation via an ndarray subclass (ISSUE 16 dual
                    # swap); a plain asarray would strip the tag
                    out = np.asanyarray(self._run_batch(
                        np.stack([p.payload for p in live])
                    ))
            except Exception as e:  # executor failure: every rider sees it
                for p in live:
                    p.resolve(error=e)
                    self._request_span(p, time.monotonic(), "batch_error",
                                       seq)
                with self._cond:
                    self.batch_errors += 1
                return
            done = time.monotonic()
            for p, row in zip(live, out):
                p.resolve(result=np.asanyarray(row))
                self._request_span(p, done, "ok", seq)
        wait_s = now - live[0].enqueue_t
        with self._cond:
            self.completed += len(live)
            self.batches += 1
            self.occupancy_sum += len(live) / bucket
        if self._on_batch is not None:
            self._on_batch(len(live), bucket, wait_s)

    def _request_span(self, p: PendingRequest, t_mono: float, outcome: str,
                      seq: int) -> None:
        """Retroactive admission→resolve span for one request, recorded
        only at `full` detail (or inside a capture window): under load the
        per-request spans are the bulk of the volume, so the coarse level
        keeps just the flush spans. Correlate with the executing flush via
        the shared `seq` attr."""
        self._tracer.record_span(
            "request", p.enqueue_wall, t_mono - p.enqueue_t, cat="serve",
            detail=True, outcome=outcome, seq=seq,
        )

    # -- shutdown ------------------------------------------------------------
    def drain(self, timeout_s: float = 60.0) -> bool:
        """Stop admitting, flush everything already accepted, return True
        once every accepted request is resolved (False on timeout — the
        caller decides whether to hard-stop)."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._qlen() or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.1))
        return True

    def close(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        """Drain (default) or reject-what's-queued, then stop the flusher."""
        if drain:
            self.drain(timeout_s)
        with self._cond:
            self._draining = True
            self._closed = True
            leftovers = [p for q in self._queues.values() for p in q]
            for q in self._queues.values():
                q.clear()
            self._cond.notify_all()
        for p in leftovers:
            p.resolve(error=DrainingError("batcher closed before execution"))
        self._thread.join(timeout=5.0)
