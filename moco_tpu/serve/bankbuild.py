"""Versioned kNN-bank builder: bulk re-embed a corpus against ONE named
checkpoint step (ISSUE 16).

The serve fleet refuses to hot-swap encoder weights under a configured
kNN bank (PR 10/13) because the bank's features live in the OLD
encoder's space. This module closes the loop: it produces a **versioned
bank artifact** that is cryptographically bound to the checkpoint it was
embedded with, so the fleet can roll engine+bank together as a verified
pair (the dual swap in service.py / fleet.py).

Artifact layout mirrors the PR 1 checkpoint-export scheme so the same
integrity machinery verifies both halves of a pair::

    <bank_dir>/<step>/bank.npz            features [N,D] f32 + labels [N] i32
    <bank_dir>/.integrity/<step>.json     manifest, written LAST

The manifest carries three bindings on top of the standard
``files:{rel:{size,sha256}}`` block (resilience/integrity.py ignores
extra top-level keys, so ``verify_step`` works unchanged):

* ``checkpoint`` — sha256 + size of the encoder payload the corpus was
  embedded with. A doctored or mismatched pair fails this check before
  any engine is built.
* ``probe`` — a few rows of a SEEDED synthetic probe batch embedded at
  build time. At swap time the serving replica re-embeds the same probe
  with the candidate engine and compares row-wise cosine: the
  space-agreement check that catches a bank whose manifest lies.
* ``shards`` — build topology, recorded for forensics only: the merge
  is in dataset-index order, so the output bytes are identical for any
  shard count (engine bit-identity is test-pinned since PR 5).

Builds are resumable and worker-death tolerant: each shard lands
atomically in ``<bank_dir>/.build/<step>/`` and a restarted build reuses
completed shards; a failing shard is retried on another worker up to
``max_shard_retries`` times. All artifact writes go through the
``atomic_*`` helpers below (temp + rename; mocolint R13 pins this).

numpy + stdlib only — the engine import stays inside the offline-build
path so the batch-lane builder (HTTP against a serve fleet) never pulls
jax.
"""

from __future__ import annotations

import io
import json
import os
import queue
import tempfile
import threading
import zipfile

import numpy as np

from moco_tpu.resilience.integrity import (
    digest_file,
    manifest_path,
    verify_step,
)

# Same seed family as the PR 13 reload probe: any party holding
# (seed, rows, image_size) regenerates the identical probe batch.
PROBE_SEED = 20130613
BANK_FILENAME = "bank.npz"
BUILD_DIRNAME = ".build"
DEFAULT_PROBE_ROWS = 8


class BankBuildError(RuntimeError):
    """A shard exhausted its retries or the corpus/checkpoint is unusable."""


# ---------------------------------------------------------------------------
# atomic, deterministic artifact writes (mocolint R13 scope)
# ---------------------------------------------------------------------------


def atomic_write_json(path: str, obj: dict) -> None:
    """Write JSON via temp + rename so readers never see a torn file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=".tmp_", suffix=".json"
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_save_npz(path: str, arrays: dict) -> None:
    """Byte-DETERMINISTIC npz write via temp + rename.

    ``np.savez`` is not reproducible across numpy versions (the zip
    member timestamps come from localtime on some versions, the 1980
    epoch on others), so the 1-shard-vs-3-shard bit-identity pin would
    be at the mercy of the environment. We write the zip by hand:
    ZIP_STORED members in sorted-name order with the ZipInfo default
    (1980) timestamp. ``np.load`` reads the result like any npz.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=".tmp_", suffix=".npz"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as zf:
                for name in sorted(arrays):
                    buf = io.BytesIO()
                    np.lib.format.write_array(
                        buf, np.ascontiguousarray(arrays[name]),
                        allow_pickle=False,
                    )
                    zf.writestr(zipfile.ZipInfo(name + ".npy"),
                                buf.getvalue())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# probe + shard geometry
# ---------------------------------------------------------------------------


def probe_batch(image_size: int, rows: int) -> np.ndarray:
    """The seeded synthetic probe batch — identical bytes for any caller
    holding (PROBE_SEED, rows, image_size). Row i is a deterministic
    prefix of one rng stream, so a consumer may compare only the first
    k <= rows rows (a serving ladder whose largest bucket is smaller
    than ``rows`` embeds a prefix)."""
    rng = np.random.default_rng(PROBE_SEED)
    return rng.integers(
        0, 256, size=(rows, image_size, image_size, 3), dtype=np.uint8
    )


def shard_ranges(n: int, shards: int) -> list:
    """[(start, end), ...] covering [0, n) in dataset-index order.

    The merge concatenates in this order, so the bank bytes do not
    depend on the shard count — only on the corpus and the engine.
    """
    if shards < 1:
        raise ValueError(f"shards must be >= 1, got {shards}")
    shards = min(shards, max(n, 1))
    base, extra = divmod(n, shards)
    out, start = [], 0
    for i in range(shards):
        end = start + base + (1 if i < extra else 0)
        out.append((start, end))
        start = end
    return out


def _shard_path(work_dir: str, start: int, end: int) -> str:
    return os.path.join(work_dir, f"shard_{start:08d}_{end:08d}.npz")


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def _embed_range(embed_fn, images: np.ndarray, start: int, end: int,
                 batch_rows: int) -> np.ndarray:
    rows = []
    for lo in range(start, end, batch_rows):
        hi = min(lo + batch_rows, end)
        out = np.asarray(embed_fn(images[lo:hi]), dtype=np.float32)
        if out.ndim != 2 or out.shape[0] != hi - lo:
            raise BankBuildError(
                f"embed_fn returned shape {out.shape} for rows "
                f"[{lo}:{hi}) — expected [{hi - lo}, D]"
            )
        rows.append(out)
    return np.concatenate(rows, axis=0) if rows else np.zeros(
        (0, 0), np.float32
    )


def build_bank(bank_dir: str, step: int, images: np.ndarray,
               labels: np.ndarray, embed_fn, *, checkpoint_path: str,
               image_size: int, shards: int = 1, workers: int = 1,
               probe_rows: int = DEFAULT_PROBE_ROWS, batch_rows: int = 64,
               emit=None, max_shard_retries: int = 3) -> dict:
    """Embed ``images`` with ``embed_fn`` into a versioned bank artifact.

    Sharded fan-out over ``workers`` threads, merge in dataset-index
    order (bit-identical for any shard count), shard files + the final
    bank written atomically, manifest written LAST so a partial build is
    never eligible for promotion. A re-run after a crash reuses every
    completed shard. Returns the manifest dict.

    ``embed_fn(batch[B,S,S,3] uint8) -> [B,D] float32`` may be an
    in-process engine closure (offline path) or an HTTP closure over a
    serve fleet's batch lane (``http_embed_fn``) — worker death in
    either shows up as an exception and the shard retries elsewhere.
    ``emit(event, **fields)`` (optional) receives build telemetry
    (build_start / shard_done / build_done).
    """
    images = np.asarray(images)
    labels = np.asarray(labels)
    if images.ndim != 4 or images.shape[0] != labels.shape[0]:
        raise BankBuildError(
            f"corpus shape mismatch: images {images.shape} vs labels "
            f"{labels.shape}"
        )
    n = int(images.shape[0])
    if n == 0:
        raise BankBuildError("empty corpus")
    ckpt_sha = digest_file(checkpoint_path)
    work_dir = os.path.join(bank_dir, BUILD_DIRNAME, str(step))
    os.makedirs(work_dir, exist_ok=True)
    ranges = shard_ranges(n, shards)
    if emit is not None:
        emit("build_start", step=step, rows=n, shards=len(ranges),
             checkpoint_sha256=ckpt_sha)

    todo: "queue.Queue" = queue.Queue()
    pending = 0
    for idx, (start, end) in enumerate(ranges):
        if os.path.exists(_shard_path(work_dir, start, end)):
            if emit is not None:
                emit("shard_done", step=step, shard=idx, start=start,
                     end=end, reused=True)
            continue
        todo.put((idx, 0))
        pending += 1

    errors: list = []
    done = threading.Event()
    lock = threading.Lock()

    def worker():
        nonlocal pending
        while not done.is_set():
            try:
                idx, attempts = todo.get(timeout=0.1)
            except queue.Empty:
                with lock:
                    if pending == 0:
                        return
                continue
            start, end = ranges[idx]
            try:
                feats = _embed_range(embed_fn, images, start, end,
                                     batch_rows)
                atomic_save_npz(_shard_path(work_dir, start, end),
                                {"features": feats})
            except Exception as e:  # retry on another worker
                if attempts + 1 >= max_shard_retries:
                    with lock:
                        errors.append(
                            BankBuildError(
                                f"shard {idx} rows [{start}:{end}) failed "
                                f"{attempts + 1}x: {e}"
                            )
                        )
                        pending -= 1
                    done.set()
                else:
                    todo.put((idx, attempts + 1))
                continue
            with lock:
                pending -= 1
            if emit is not None:
                emit("shard_done", step=step, shard=idx, start=start,
                     end=end, reused=False)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, workers))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]

    # merge in dataset-index order — byte-identical for any shard count
    parts = []
    for start, end in ranges:
        with np.load(_shard_path(work_dir, start, end)) as z:
            part = z["features"].astype(np.float32, copy=False)
        if part.shape[0] != end - start:
            raise BankBuildError(
                f"shard rows [{start}:{end}) holds {part.shape[0]} rows "
                "— stale shard file? delete the .build dir and rerun"
            )
        parts.append(part)
    features = np.concatenate(parts, axis=0)
    probe = probe_batch(image_size, probe_rows)
    probe_feats = np.asarray(embed_fn(probe), dtype=np.float32)

    step_dir = os.path.join(bank_dir, str(step))
    bank_path = os.path.join(step_dir, BANK_FILENAME)
    atomic_save_npz(bank_path, {
        "features": features.astype(np.float32, copy=False),
        "labels": labels.astype(np.int32, copy=False),
    })
    manifest = {
        "v": 1,
        "kind": "bank",
        "step": int(step),
        "rows": int(features.shape[0]),
        "feat_dim": int(features.shape[1]),
        "shards": len(ranges),
        "files": {
            BANK_FILENAME: {
                "size": os.path.getsize(bank_path),
                "sha256": digest_file(bank_path),
            },
        },
        "checkpoint": {
            "file": os.path.basename(checkpoint_path),
            "size": os.path.getsize(checkpoint_path),
            "sha256": ckpt_sha,
        },
        "probe": {
            "seed": PROBE_SEED,
            "rows": int(probe_rows),
            "image_size": int(image_size),
            "features": [[float(x) for x in row] for row in probe_feats],
        },
    }
    # manifest LAST: only now is the artifact eligible for promotion
    atomic_write_json(manifest_path(bank_dir, step), manifest)
    _cleanup_build_dir(work_dir)
    if emit is not None:
        emit("build_done", step=step, rows=int(features.shape[0]),
             feat_dim=int(features.shape[1]), shards=len(ranges),
             manifest_sha256=digest_file(manifest_path(bank_dir, step)))
    return manifest


def _cleanup_build_dir(work_dir: str) -> None:
    try:
        for name in os.listdir(work_dir):
            os.unlink(os.path.join(work_dir, name))
        os.rmdir(work_dir)
        parent = os.path.dirname(work_dir)
        if not os.listdir(parent):
            os.rmdir(parent)
    except OSError:
        pass  # best-effort; a leftover .build dir never promotes


# ---------------------------------------------------------------------------
# load + verify (the serving side)
# ---------------------------------------------------------------------------


def load_bank(path: str):
    """(features [N,D] f32, labels [N], meta|None) from a bank npz.

    Works for BOTH a plain npz (the pre-ISSUE-16 --knn-bank contract)
    and a versioned artifact — ``meta`` is None when the npz has no
    adjacent manifest, so bank-free and legacy deployments are
    untouched.
    """
    bank = np.load(path)
    if "features" not in bank or "labels" not in bank:
        raise ValueError(
            f"--knn-bank {path!r} needs `features` [N,D] and `labels` "
            "[N] arrays"
        )
    return bank["features"], bank["labels"], read_bank_meta(path)


def read_bank_meta(bank_npz_path: str):
    """Manifest-derived metadata for a versioned bank npz, or None.

    A versioned bank lives at ``<bank_dir>/<step>/bank.npz`` with its
    manifest at ``<bank_dir>/.integrity/<step>.json``. Any other layout
    (plain npz, digit-less parent) is a legacy bank: None.
    """
    step_dir = os.path.dirname(os.path.abspath(bank_npz_path))
    step_name = os.path.basename(step_dir)
    if not step_name.isdigit():
        return None
    bank_dir = os.path.dirname(step_dir)
    step = int(step_name)
    mpath = manifest_path(bank_dir, step)
    if not os.path.exists(mpath):
        return None
    with open(mpath) as f:
        manifest = json.load(f)
    return {
        "step": step,
        "path": os.path.abspath(bank_npz_path),
        "bank_dir": bank_dir,
        "manifest_path": mpath,
        "manifest_sha256": digest_file(mpath),
        "rows": manifest.get("rows"),
        "feat_dim": manifest.get("feat_dim"),
        "shards": manifest.get("shards"),
        "checkpoint_sha256": (manifest.get("checkpoint") or {}).get("sha256"),
        "probe": manifest.get("probe"),
    }


def verify_bank(bank_dir: str, step: int):
    """integrity.verify_step over the bank layout: None when the npz
    matches its manifest hashes, else the failure reason."""
    return verify_step(bank_dir, step)


def probe_agreement(embed_fn, meta) -> float:
    """Mean row-wise cosine between the bank's recorded probe features
    and the same probe rows embedded by ``embed_fn`` — the bank/encoder
    space-agreement score. 1.0 = identical space; a bank whose manifest
    lies about its checkpoint scores near chance.

    Embeds only as many rows as ``embed_fn`` can take in one call if
    the caller pre-slices; rows are a deterministic prefix of one rng
    stream, so comparing the first k rows is sound.
    """
    probe = meta.get("probe") or {}
    recorded = np.asarray(probe.get("features", ()), dtype=np.float32)
    if recorded.ndim != 2 or recorded.shape[0] == 0:
        raise ValueError("bank manifest records no probe rows")
    batch = probe_batch(int(probe["image_size"]), recorded.shape[0])
    ours = np.asarray(embed_fn(batch), dtype=np.float32)
    k = min(recorded.shape[0], ours.shape[0])
    if k == 0 or ours.shape[1] != recorded.shape[1]:
        return 0.0
    a, b = recorded[:k], ours[:k]
    an = np.linalg.norm(a, axis=1)
    bn = np.linalg.norm(b, axis=1)
    denom = np.maximum(an * bn, 1e-12)
    return float(np.mean(np.sum(a * b, axis=1) / denom))


# ---------------------------------------------------------------------------
# batch-lane embed_fn: build over a running serve fleet
# ---------------------------------------------------------------------------


def http_embed_fn(base_url: str, *, timeout_s: float = 30.0):
    """embed_fn closure over a serve fleet's POST /v1/embed lane.

    Each row goes out as one request (the replica's batcher coalesces
    them into bucket-ladder batches); a dead worker surfaces as an
    exception and build_bank retries the shard elsewhere. NOTE: the
    fleet must be SERVING the target checkpoint — a bank built through
    replicas on older weights would fail the space-agreement check at
    swap time (by design).
    """
    import urllib.request

    url = base_url.rstrip("/") + "/v1/embed"

    def embed(batch: np.ndarray) -> np.ndarray:
        rows = []
        for img in np.asarray(batch):
            body = json.dumps({
                "pixels": img.astype(np.uint8).tolist(),
                # tiered admission (ISSUE 20): a fleet-mode bank build
                # is throughput work — it rides the batch lane so a
                # build flood can never shed interactive traffic
                "tier": "batch",
            }).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                payload = json.loads(resp.read().decode())
            rows.append(np.asarray(payload["embedding"], np.float32))
        return np.stack(rows, axis=0)

    return embed
