"""Content-hash embedding LRU (ISSUE 5 tentpole part 4).

Identical inputs embed identically under a frozen encoder, so a repeat
request is pure waste on the device — the serve-side analogue of the
decode-once observation behind `data/canvas_cache.py`, whose
byte-budgeted LRU pattern this reuses: a MiB budget over stored bytes,
eviction from the LRU end, entries immutable by convention, dict
bookkeeping under a lock with the heavy work (hashing) outside it.

Keys are content hashes (sha256 over shape + dtype + pixel bytes), not
client-supplied ids: two clients sending the same image share one entry,
and a client mutating its buffer after submit can never corrupt a stored
embedding (the stored row is a private copy)."""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np


class EmbeddingCache:
    """Byte-budgeted LRU of `content_key -> embedding row`."""

    def __init__(self, cache_mb: int):
        if cache_mb <= 0:
            raise ValueError(f"cache_mb must be positive, got {cache_mb}")
        self.budget_bytes = int(cache_mb) * 2**20
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, np.ndarray] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key_for(image: np.ndarray) -> str:
        """Content hash of one image. Shape and dtype are folded in so a
        reshaped view of the same bytes is a different key — embeddings
        are functions of the IMAGE, not of its raveled buffer."""
        h = hashlib.sha256()
        h.update(repr((image.shape, str(image.dtype))).encode("ascii"))
        h.update(image.tobytes())
        return h.hexdigest()

    def get(self, key: str) -> np.ndarray | None:
        with self._lock:
            row = self._entries.get(key)
            if row is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return row  # immutable by convention; stored rows are copies

    def put(self, key: str, embedding: np.ndarray) -> None:
        row = np.array(embedding)  # private copy: callers keep their buffer
        cost = row.nbytes
        if cost > self.budget_bytes:
            return  # larger than the whole budget: never cached
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._bytes + cost > self.budget_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
            self._entries[key] = row
            self._bytes += cost

    def clear(self) -> None:
        """Drop every entry (hot weight reload: cached rows are functions
        of the old weights). Hit/miss counters survive — they describe the
        process's traffic, not one model version."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def hit_rate(self) -> float:
        lookups = self.hits + self.misses
        return self.hits / lookups if lookups else 0.0
