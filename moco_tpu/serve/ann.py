"""IVF-style ANN index over a versioned kNN bank (ISSUE 20).

Exact kNN over a million-row bank costs N·D flops per query on EVERY
replica. The index here cuts that to nprobe/cells of the bank with the
classic IVF recipe: a k-means coarse quantizer over the l2-normalized
bank rows, bank rows re-ordered cell-contiguously, and per-cell EXACT
cosine rerank inside the probed cells — the same similarity + exp(sim/T)
vote protocol as ``ops/knn.knn_predict``, so an exact-mode deployment
(``ann_cells=0``) stays bit-identical to today's ``/v1/knn``.

Three contracts matter more than speed:

* **Determinism (R9 family).** The build is a pure function of the bank
  BYTES + (cells, seed): seeded rng permutation init, fixed Lloyd
  iterations, ``np.argmax``/stable-sort tie-breaks, deterministic
  empty-cell re-seeding. Since bank bytes are already shard-count
  invariant (ISSUE 16), a 1-shard and an N-shard bank build yield a
  byte-identical ``ann.npz`` and manifest.
* **Atomicity (R13).** ``ann.npz`` lands via bankbuild's
  ``atomic_save_npz`` (deterministic ZIP_STORED bytes), the manifest
  via ``atomic_write_json`` — manifest LAST, so a torn index is never
  promotable.
* **Pairing.** The manifest (``.integrity/<step>.ann.json``, next to
  the bank's own manifest) binds the index sha to the bank sha AND the
  bank's checkpoint sha: a replica refuses an index whose bank bytes
  drifted, exactly like the bank refuses a drifted checkpoint.

Fleet sharding is CELL-partitioned: replica ``shard`` of ``shards``
owns cells where ``cell % shards == shard`` and answers with its local
top candidates; the stdlib-only router fans out and merges (fleet.py
never imports this module — candidates cross the wire as plain JSON).

numpy + stdlib only: no jax on this path, nothing to compile at serve
time (mocolint R6 pins it).
"""

from __future__ import annotations

import json
import os

import numpy as np

from moco_tpu.resilience.integrity import INTEGRITY_DIRNAME, digest_file
from moco_tpu.serve.bankbuild import (
    PROBE_SEED,
    atomic_save_npz,
    atomic_write_json,
    load_bank,
    read_bank_meta,
)

ANN_FILENAME = "ann.npz"
# fixed build seed — part of the artifact contract (manifest records it;
# changing it is a format bump, not a knob)
ANN_SEED = 20200607
ANN_KMEANS_ITERS = 10


class AnnIndexError(ValueError):
    """A missing / torn / mispaired index artifact."""


def ann_index_path(bank_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(bank_dir), str(step), ANN_FILENAME)


def ann_manifest_path(bank_dir: str, step: int) -> str:
    """Sidecar manifest for the index. Lives in ``.integrity/`` next to
    the bank's own ``<step>.json`` but under ``<step>.ann.json`` so
    ``verify_bank``/``verify_step`` semantics over the bank manifest are
    untouched."""
    return os.path.join(
        os.path.abspath(bank_dir), INTEGRITY_DIRNAME, f"{step}.ann.json"
    )


def _l2(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.float32)
    return x / np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-12)


def _kmeans(rows: np.ndarray, cells: int, iters: int, seed: int):
    """Deterministic spherical k-means: (centroids [C,D], assign [N]).

    Every tie-break is pinned: init is a seeded permutation prefix,
    assignment is ``np.argmax`` (lowest cell wins ties), empty cells are
    re-seeded with the rows WORST-served by their current centroid
    (stable sort order), updates use ``np.add.at`` (sequential
    accumulation). Same rows + cells + seed => same float32 output.
    """
    n = rows.shape[0]
    rng = np.random.default_rng(seed)
    centroids = rows[np.sort(rng.permutation(n)[:cells])].copy()
    assign = np.zeros(n, dtype=np.int64)
    for _ in range(iters):
        sims = rows @ centroids.T                      # [N, C]
        assign = np.argmax(sims, axis=1)
        counts = np.bincount(assign, minlength=cells)
        sums = np.zeros_like(centroids)
        np.add.at(sums, assign, rows)
        live = counts > 0
        centroids[live] = sums[live] / counts[live, None]
        empty = np.flatnonzero(~live)
        if empty.size:
            # rows least similar to their own centroid, stable order
            own = sims[np.arange(n), assign]
            worst = np.argsort(own, kind="stable")[: empty.size]
            centroids[empty] = rows[worst]
        centroids = _l2(centroids)
    assign = np.argmax(rows @ centroids.T, axis=1)
    return centroids, assign.astype(np.int64)


def build_ann_index(bank_dir: str, step: int, *, cells: int,
                    kmeans_iters: int = ANN_KMEANS_ITERS,
                    seed: int = ANN_SEED, emit=None) -> dict:
    """Build + atomically persist the IVF index for one bank step.

    Returns the manifest dict. The artifact is ``<step>/ann.npz`` with
    ``centroids [C,D] f32``, ``row_order [N] i64`` (bank row index of
    each cell-contiguous slot), ``cell_offsets [C+1] i64``; the manifest
    (written LAST) binds index sha -> bank sha -> checkpoint sha.
    """
    if cells < 1:
        raise ValueError(f"ann cells must be >= 1, got {cells}")
    bank_path = os.path.join(os.path.abspath(bank_dir), str(step),
                             "bank.npz")
    features, _labels, meta = load_bank(bank_path)
    if meta is None:
        raise AnnIndexError(
            f"bank at {bank_path!r} has no integrity manifest — ANN "
            "indexes pair only with versioned banks"
        )
    n = features.shape[0]
    cells = min(cells, n)
    rows = _l2(features)
    centroids, assign = _kmeans(rows, cells, kmeans_iters, seed)
    row_order = np.argsort(assign, kind="stable").astype(np.int64)
    counts = np.bincount(assign, minlength=cells)
    cell_offsets = np.zeros(cells + 1, dtype=np.int64)
    np.cumsum(counts, out=cell_offsets[1:])

    index_path = ann_index_path(bank_dir, step)
    atomic_save_npz(index_path, {
        "centroids": centroids,
        "row_order": row_order,
        "cell_offsets": cell_offsets,
    })
    manifest = {
        "v": 1,
        "kind": "ann",
        "step": int(step),
        "cells": int(cells),
        "rows": int(n),
        "feat_dim": int(features.shape[1]),
        "kmeans_iters": int(kmeans_iters),
        "seed": int(seed),
        "files": {
            ANN_FILENAME: {
                "size": os.path.getsize(index_path),
                "sha256": digest_file(index_path),
            },
        },
        "bank": {
            "file": "bank.npz",
            "sha256": digest_file(bank_path),
        },
        "checkpoint_sha256": meta.get("checkpoint_sha256"),
    }
    atomic_write_json(ann_manifest_path(bank_dir, step), manifest)
    if emit is not None:
        emit("ann_built", step=int(step), cells=int(cells), rows=int(n))
    return manifest


def verify_ann(bank_dir: str, step: int):
    """None when the index verifies against its manifest AND its bank
    binding, else the failure reason (same contract as verify_bank)."""
    mpath = ann_manifest_path(bank_dir, step)
    if not os.path.exists(mpath):
        return f"no ann manifest at {mpath}"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return f"unreadable ann manifest: {e}"
    index_path = ann_index_path(bank_dir, step)
    if not os.path.exists(index_path):
        return f"manifested index missing: {index_path}"
    rec = (manifest.get("files") or {}).get(ANN_FILENAME) or {}
    if os.path.getsize(index_path) != rec.get("size"):
        return "ann.npz size mismatch"
    if digest_file(index_path) != rec.get("sha256"):
        return "ann.npz sha256 mismatch"
    bank_path = os.path.join(os.path.abspath(bank_dir), str(step),
                             "bank.npz")
    want_bank = (manifest.get("bank") or {}).get("sha256")
    if not os.path.exists(bank_path):
        return f"paired bank missing: {bank_path}"
    if digest_file(bank_path) != want_bank:
        return "bank bytes drifted since the index was built"
    return None


def load_ann(bank_npz_path: str):
    """(arrays dict, manifest dict) for the index paired with a bank
    npz, or None when the bank has no (verifying) index.

    Raises AnnIndexError on a PRESENT-but-torn/mispaired index — silent
    fallback to exact over a bad artifact would mask corruption.
    """
    meta = read_bank_meta(bank_npz_path)
    if meta is None:
        return None
    bank_dir, step = meta["bank_dir"], meta["step"]
    mpath = ann_manifest_path(bank_dir, step)
    if not os.path.exists(mpath):
        return None
    reason = verify_ann(bank_dir, step)
    if reason is not None:
        raise AnnIndexError(f"ann index for step {step} rejected: {reason}")
    with open(mpath) as f:
        manifest = json.load(f)
    with np.load(ann_index_path(bank_dir, step)) as z:
        arrays = {k: z[k] for k in ("centroids", "row_order",
                                    "cell_offsets")}
    return arrays, manifest


def vote(candidates, temperature: float, num_classes: int) -> int:
    """exp(sim/T) class vote over (sim, label) pairs — the ops/knn
    protocol, restated over merged candidates. Ties break to the lowest
    label (argmax semantics). fleet.py reimplements this in pure python
    for the router merge; test_serve_scale pins the two equal."""
    weights = np.zeros(num_classes, dtype=np.float64)
    t = max(float(temperature), 1e-8)
    for sim, label in candidates:
        weights[int(label)] += float(np.exp(float(sim) / t))
    return int(np.argmax(weights))


class AnnShard:
    """One replica's cell-partitioned view of an IVF index.

    ``shard`` of ``shards`` owns cells with ``cell % shards == shard``
    (shards=1 => the whole index). ``search`` probes the top-``nprobe``
    OWNED cells by centroid similarity, exact-reranks their rows, and
    returns the top-``rerank`` candidates; ``classify`` votes over them
    locally (the single-replica serving path), while the fleet router
    merges ``search`` candidates across shards instead.
    """

    def __init__(self, features, labels, arrays, *, shard: int = 0,
                 shards: int = 1, nprobe: int = 8, rerank: int = 200,
                 temperature: float = 0.07, num_classes: int = 0):
        if shards < 1 or not (0 <= shard < shards):
            raise ValueError(
                f"need 0 <= shard < shards, got shard={shard} "
                f"shards={shards}"
            )
        centroids = np.asarray(arrays["centroids"], np.float32)
        row_order = np.asarray(arrays["row_order"], np.int64)
        offsets = np.asarray(arrays["cell_offsets"], np.int64)
        n, cells = row_order.shape[0], centroids.shape[0]
        if features.shape[0] != n or offsets.shape[0] != cells + 1:
            raise AnnIndexError(
                f"index shape mismatch: bank rows {features.shape[0]} "
                f"vs row_order {n}, cells {cells} vs offsets "
                f"{offsets.shape[0] - 1}"
            )
        self.shard, self.shards = int(shard), int(shards)
        self.cells = cells
        self.nprobe = max(1, int(nprobe))
        self.rerank = max(1, int(rerank))
        self.temperature = float(temperature)
        labels = np.asarray(labels)
        self.num_classes = int(num_classes) if num_classes else (
            int(labels.max()) + 1 if labels.size else 1)
        self._centroids = centroids
        self._offsets = offsets
        self._owned = np.flatnonzero(
            np.arange(cells, dtype=np.int64) % shards == shard)
        # cell-contiguous copies so a probe reads dense slices
        self._rows = _l2(features)[row_order]
        self._labels = labels[row_order].astype(np.int64)
        self._row_ids = row_order  # slot -> original bank row index
        self._owned_slots = (np.concatenate(
            [np.arange(offsets[c], offsets[c + 1]) for c in self._owned]
        ) if self._owned.size else np.zeros(0, dtype=np.int64))
        self.owned_rows = int(self._owned_slots.size)

    def search(self, embedding, *, k: int | None = None,
               nprobe: int | None = None):
        """Top candidates among this shard's owned cells.

        Returns (sims [M] f32, labels [M] i64, rows [M] i64) sorted by
        descending similarity, ties to the lower cell-slot (stable) —
        ``rows`` are original bank row indices, which is what the recall
        probe compares against exact search.
        """
        q = _l2(np.asarray(embedding, np.float32).reshape(-1))
        probe = min(nprobe or self.nprobe, self._owned.size)
        if probe == 0:
            empty = np.zeros(0)
            return (empty.astype(np.float32), empty.astype(np.int64),
                    empty.astype(np.int64))
        csims = self._centroids[self._owned] @ q
        # descending centroid sim, ties to the lower cell id
        order = np.lexsort((self._owned, -csims))[:probe]
        picked = self._owned[order]
        spans = [np.arange(self._offsets[c], self._offsets[c + 1])
                 for c in picked]
        slots = (np.concatenate(spans) if spans
                 else np.zeros(0, dtype=np.int64))
        if slots.size == 0:
            empty = np.zeros(0)
            return (empty.astype(np.float32), empty.astype(np.int64),
                    empty.astype(np.int64))
        sims = self._rows[slots] @ q
        top = min(k or self.rerank, slots.size)
        # descending sim, ties to the lower slot (deterministic merge)
        best = np.lexsort((slots, -sims))[:top]
        sel = slots[best]
        return (sims[best].astype(np.float32), self._labels[sel],
                self._row_ids[sel])

    def classify(self, embedding, *, k: int | None = None):
        """(predicted class, candidate count) by local exp(sim/T) vote —
        the single-process ANN serving path (shards=1 sees the whole
        bank; a true shard votes over its partition only, and the fleet
        merge is the authoritative answer)."""
        sims, labels, _rows = self.search(embedding, k=k)
        if sims.size == 0:
            return 0, 0
        pred = vote(zip(sims.tolist(), labels.tolist()),
                    self.temperature, self.num_classes)
        return pred, int(sims.size)

    def recall_probe(self, *, queries: int = 64,
                     seed: int = PROBE_SEED) -> float:
        """recall@1 vs EXACT search over this shard's own rows, on a
        seeded probe set of perturbed bank rows (near the data manifold,
        so the measure reflects real traffic). Deterministic: same
        index + seed => same score. The ISSUE 20 gate pins >= 0.95 on
        the shards=1 view."""
        owned = self._owned_slots
        if owned.size == 0:
            return 1.0
        rng = np.random.default_rng(seed)
        base = owned[rng.integers(0, owned.size,
                                  size=min(queries, owned.size))]
        noise = rng.standard_normal(
            (base.size, self._rows.shape[1])).astype(np.float32)
        qs = _l2(self._rows[base] + 0.1 * noise)
        hits = 0
        for q in qs:
            exact_sims = self._rows[owned] @ q
            exact_slot = owned[np.lexsort((owned, -exact_sims))[0]]
            _sims, _labels, rows = self.search(q, k=1)
            hits += int(rows.size > 0
                        and rows[0] == self._row_ids[exact_slot])
        return hits / qs.shape[0]

    def stats(self) -> dict:
        return {
            "cells": self.cells,
            "nprobe": self.nprobe,
            "rerank": self.rerank,
            "shard": self.shard,
            "shards": self.shards,
            "owned_rows": self.owned_rows,
        }
