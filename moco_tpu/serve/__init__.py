"""moco_tpu.serve — online embedding service (ISSUE 5).

The repo's first non-training workload: a request-driven inference
runtime over a pretraining checkpoint's momentum encoder. Layers:

    batcher.py   dynamic micro-batching (flush on size OR deadline),
                 bounded admission queue, load shedding, drain semantics
    engine.py    bucketed-compile jitted apply (pad to 1/8/32/128 —
                 a fixed program set, zero recompiles under load)
    cache.py     content-hash embedding LRU (byte-budgeted, the
                 data/canvas_cache.py pattern)
    service.py   the request path: validation → cache → batcher →
                 engine (+ optional kNN classify), telemetry snapshots
    http.py      stdlib-HTTP front end (tools/serve.py mounts it)

Train-free by lint (tools/lint_robustness.py R6): nothing here may
import train, train_step, or optimizer modules — the server stays
import-light and can never grow a training dependency by accident."""

from moco_tpu.serve.batcher import (
    DeadlineExceededError,
    DrainingError,
    MicroBatcher,
    OverloadedError,
    PendingRequest,
    RejectionError,
    bucket_for,
)
from moco_tpu.serve.cache import EmbeddingCache
from moco_tpu.serve.engine import DEFAULT_BUCKETS, EmbeddingEngine
from moco_tpu.serve.http import ServeFrontend, decode_image
from moco_tpu.serve.service import EmbedService

__all__ = [
    "DEFAULT_BUCKETS",
    "DeadlineExceededError",
    "DrainingError",
    "EmbedService",
    "EmbeddingCache",
    "EmbeddingEngine",
    "MicroBatcher",
    "OverloadedError",
    "PendingRequest",
    "RejectionError",
    "ServeFrontend",
    "bucket_for",
    "decode_image",
]
