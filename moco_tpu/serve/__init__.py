"""moco_tpu.serve — online embedding service (ISSUE 5) + serve fleet
(ISSUE 10).

The repo's first non-training workload: a request-driven inference
runtime over a pretraining checkpoint's momentum encoder. Layers:

    batcher.py   dynamic micro-batching (flush on size OR deadline),
                 bounded admission queue, load shedding, drain semantics
    engine.py    bucketed-compile jitted apply (pad to 1/8/32/128 —
                 a fixed program set, zero recompiles under load)
    cache.py     content-hash embedding LRU (byte-budgeted, the
                 data/canvas_cache.py pattern)
    service.py   the request path: validation → cache → batcher →
                 engine (+ optional kNN classify), hot weight reload,
                 telemetry snapshots
    http.py      stdlib-HTTP front end (tools/serve.py mounts it)
    bankbuild.py versioned kNN-bank builder (ISSUE 16): sharded,
                 resumable corpus re-embed bound to its checkpoint by
                 an integrity manifest — the dual swap's other half
    fleet.py     replicated-serving control plane (ISSUE 10): fleet
                 supervisor over N serve.py replicas, health-routed
                 front-end router, checkpoint watcher with integrity-
                 verified hot reload — PURE stdlib, never numpy/jax

Train-free by lint (mocolint R6/R11): nothing here may import train,
train_step, or optimizer modules — the server stays import-light and can
never grow a training dependency by accident.

This __init__ is LAZY (PEP 562, the telemetry/__init__ pattern): the
fleet supervisor imports `moco_tpu.serve.fleet` — which executes this
package body — and must stay importable without numpy or jax (the
mocolint R11 fleet-stdlib-only boundary walks ancestor __init__s).
Eagerly importing batcher/engine here would drag numpy into every fleet
process; instead each public name resolves its submodule on first
attribute access, so `from moco_tpu.serve import EmbedService` keeps
working unchanged while `import moco_tpu.serve.fleet` touches nothing
heavy."""

from __future__ import annotations

import importlib

# public name -> submodule that defines it
_EXPORTS = {
    "DeadlineExceededError": "batcher",
    "DrainingError": "batcher",
    "MicroBatcher": "batcher",
    "OverloadedError": "batcher",
    "PendingRequest": "batcher",
    "RejectionError": "batcher",
    "bucket_for": "batcher",
    "EmbeddingCache": "cache",
    "DEFAULT_BUCKETS": "engine",
    "EmbeddingEngine": "engine",
    "ServeFrontend": "http",
    "decode_image": "http",
    "BankMismatchError": "service",
    "CollapsedCheckpointError": "service",
    "EmbedService": "service",
    "ReloadRefusedError": "service",
    "BankBuildError": "bankbuild",
    "build_bank": "bankbuild",
    "load_bank": "bankbuild",
    "read_bank_meta": "bankbuild",
    "CheckpointWatcher": "fleet",
    "FleetPolicy": "fleet",
    "FleetRouter": "fleet",
    "FleetSupervisor": "fleet",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        submodule = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(
        importlib.import_module(f"{__name__}.{submodule}"), name
    )
    globals()[name] = value  # cache: later accesses skip __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
