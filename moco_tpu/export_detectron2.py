"""Detectron2 checkpoint converter (rebuild of
`detection/convert-pretrain-to-detectron2.py`, SURVEY §2.6/§3.4).

The reference's transfer story: strip `module.encoder_q.`, rename torchvision
ResNet keys to Detectron2's C4 naming, write a `.pkl` that Detectron2's
checkpointer loads with `matching_heuristics`. Same contract here, torch-free
(pure numpy + pickle), consuming either our safetensors/npz export or —
since the dialect matches — any reference-style flat checkpoint.

Name map (torchvision → Detectron2 R50-C4):
    conv1.*               → backbone prefix `stem.conv1.*`
    bn1.{w,b,rm,rv}       → `stem.conv1.norm.{weight,bias,running_mean,running_var}`
    layer{i}.{j}.convK/bnK → `res{i+1}.{j}.convK{,.norm}`
    layer{i}.{j}.downsample.0/1 → `res{i+1}.{j}.shortcut{,.norm}`
    fc.*                  → dropped (detection has no classifier head)

Usage: python -m moco_tpu.export_detectron2 encoder.safetensors out.pkl
"""

from __future__ import annotations

import argparse
import pickle

import numpy as np

from moco_tpu.checkpoint import detect_dialect, import_encoder_q

_BN_LEAVES = {
    "weight": "norm.weight",
    "bias": "norm.bias",
    "running_mean": "norm.running_mean",
    "running_var": "norm.running_var",
}


def torchvision_flat_to_detectron2(
    flat: dict[str, np.ndarray], prefix: str = "module.encoder_q."
) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for name, arr in flat.items():
        if not name.startswith(prefix):
            continue
        name = name[len(prefix):]
        parts = name.split(".")
        if parts[0].startswith("fc"):
            continue
        if parts[-1] == "num_batches_tracked":
            continue  # torch BN bookkeeping; Detectron2 has no equivalent
        if parts[0] == "conv1":
            out["stem.conv1." + ".".join(parts[1:])] = np.asarray(arr)
        elif parts[0] == "bn1":
            out["stem.conv1." + _BN_LEAVES[parts[1]]] = np.asarray(arr)
        elif parts[0].startswith("layer"):
            stage = int(parts[0][len("layer"):])
            block = parts[1]
            rest = parts[2:]
            base = f"res{stage + 1}.{block}"
            if rest[0].startswith("conv"):
                out[f"{base}.{rest[0]}.{'.'.join(rest[1:])}"] = np.asarray(arr)
            elif rest[0].startswith("bn"):
                conv = "conv" + rest[0][len("bn"):]
                out[f"{base}.{conv}.{_BN_LEAVES[rest[1]]}"] = np.asarray(arr)
            elif rest[0] == "downsample":
                leaf = (
                    "shortcut." + ".".join(rest[2:])
                    if rest[1] == "0"
                    else "shortcut." + _BN_LEAVES[rest[2]]
                )
                out[f"{base}.{leaf}"] = np.asarray(arr)
            else:
                raise ValueError(f"unexpected key {name!r}")
        else:
            raise ValueError(f"unexpected key {name!r}")
    if not out:
        raise ValueError(f"no {prefix}* entries found")
    return out


def convert(src: str, dst: str, prefix: str = "module.encoder_q.") -> dict:
    flat = import_encoder_q(src)
    if prefix == "module.encoder_q.":
        # shared dialect table (checkpoint.CHECKPOINT_DIALECTS): a ViT or
        # v3-tree export has no Detectron2 C4 mapping — say so up front
        # instead of the generic "no entries found" tail error. A custom
        # prefix opts out: the caller is naming their own dialect.
        dialect = detect_dialect(flat)
        if dialect != "torchvision_encoder_q":
            raise ValueError(
                f"{src!r} is a {dialect!r} checkpoint; only the torchvision "
                "`module.encoder_q.*` ResNet dialect maps onto Detectron2 "
                "C4 names (ViT/v3-tree backbones have no C4 equivalent)"
            )
    model = torchvision_flat_to_detectron2(flat, prefix)
    obj = {
        "model": model,
        "__author__": "moco_tpu",
        "matching_heuristics": True,
    }
    with open(dst, "wb") as f:
        pickle.dump(obj, f)
    return model


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("input", help="exported encoder (.safetensors / .npz)")
    parser.add_argument("output", help="Detectron2-format .pkl")
    parser.add_argument("--prefix", default="module.encoder_q.")
    args = parser.parse_args(argv)
    model = convert(args.input, args.output, args.prefix)
    from moco_tpu.utils.logging import info

    info(f"wrote {args.output} with {len(model)} tensors")


if __name__ == "__main__":
    main()
