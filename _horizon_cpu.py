"""CPU-scale learning-dynamics run (config-1 shape at micro scale): evidence
for hardening test_smoke_train thresholds. Writes runs/horizon_cpu_r2.log."""
import json, os, time
from moco_tpu.parallel.mesh import force_cpu_devices
force_cpu_devices(8)
import jax
from moco_tpu.config import get_preset
from moco_tpu.train import train

cfg = get_preset("cifar10-moco-v1").replace(
    arch="resnet_tiny", cifar_stem=True, dataset="synthetic", image_size=16,
    batch_size=64, num_negatives=512, embed_dim=32, lr=0.12, cos=True,
    epochs=24, steps_per_epoch=64,   # 1536 steps
    knn_monitor=True, knn_bank_size=1024, num_classes=10,
    ckpt_dir="", tb_dir="", print_freq=9999, num_workers=1,
)
t0 = time.time()
state, metrics = train(cfg)
print(json.dumps({"final_knn_top1": metrics.get("knn_top1"),
                  "final_loss": metrics.get("loss"),
                  "steps": int(state.step), "wall_s": round(time.time()-t0,1)}))
