"""North-star benchmark: MoCo-v2 ResNet-50 pretrain throughput (imgs/sec/chip).

Default mode runs the REAL training step — on-device two-crop augmentation +
both encoder forwards + ShuffleBN collectives + InfoNCE + backward + SGD +
donated queue update — on whatever chips are present (the sandbox exposes
one), with the full 65536-slot queue and bf16 compute, and compares per-chip
throughput against the reference's 8xV100 number (BASELINE.md: ~1340 imgs/s
global = 168 imgs/s/GPU, derived from the README's ~53 h / 200 epochs).

Prints metric-bearing JSON lines ({"metric", "value", "unit",
"vs_baseline", ...}); consumers take the LAST one (a provisional CPU-proxy
line may precede the final consolidated record — see Resilience below).

Extra modes (VERDICT r1: the input path must be measured, not amortized away):
  --mode input   host JPEG→staging throughput (native C++ loader) across
                 thread counts, plus the PIL fallback — one JSON line with
                 the best imgs/sec and per-thread detail.
  --mode e2e     the timed train loop fed by epoch_loader + ImageFolder over
                 a generated JPEG tree (honest host-decode-in-the-loop
                 number) — one JSON line, imgs/sec/chip.

Resilience (VERDICT r2 #1, r3 #1): the default entry point is an
ORCHESTRATOR that never touches a JAX backend itself and fits a HARD total
budget (default 600 s, `MOCO_TPU_BENCH_BUDGET_S`) well under the driver's
outer timeout — round 3's ladder (1500+900+1200 s) was killed at rc=124
with nothing on stdout, erasing even the fact the TPU was down. Cheap-first
design: the ~45 s CPU-proxy child runs FIRST and its record is printed
IMMEDIATELY as a provisional line, so a number exists from minute one no
matter when an external SIGKILL lands.

The TPU success path (VERDICT r4 #2) is sized to actually SUCCEED, not
just survive outage: a ~90 s LIVENESS PROBE child (`jax.devices()` only)
decides whether a chip is reachable before any expensive attempt. Dead
probe → the TPU attempt is skipped entirely (no 330 s hang) and the budget
funds the CPU e2e proxy. Live probe → the step child gets EVERYTHING that
remains minus a flush margin (`plan_tpu_attempt`, unit-tested cap
arithmetic) — ~460 s on a fresh 600 s budget, vs r4's fixed 330 s — and
the children enable a persistent XLA compilation cache
(`moco_tpu.utils.cache`), so the first healthy contact pays the compile
once and later runs spend the window measuring. One retry with
`MOCO_TPU_DISABLE_PALLAS` runs only when the failure is FAST (compile
error shape, e.g. a Mosaic rejection of the blur kernel), never on a
hang; the shipping default has `fused_bn_conv` OFF until
`tools/_fused_validate.py` passes on a chip, so the fused family is ruled
out by default rather than by retry. On success the upgraded record is
printed as a NEW line — consumers take the LAST metric-bearing JSON line
(the same convention `_run_child` applies to its children). A
SIGTERM/SIGINT handler flushes the best-so-far record, so even a graceful
kill mid-attempt yields the full evidence trail. Input and e2e child
summaries are folded into the final record's "input"/"e2e" keys (VERDICT
r3 #8) when the budget allows; on a live-chip day the e2e slot upgrades
to the real TPU measurement if the step child leaves >120 s.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time


# per-mode metric/unit for the last-resort error record, matching the names
# the success path would have emitted so consumers can pair them
BENCH_FALLBACK_METRICS = {
    "step": ("moco_v2_r50_pretrain_throughput_per_chip", "imgs/sec/chip"),
    "input": ("host_staging_throughput", "imgs/sec"),
    "e2e": ("moco_v2_r50_e2e_input_fed_throughput_per_chip", "imgs/sec/chip"),
    "serve": ("serve_embed_p95_latency_ms", "ms"),
}

# TPU attempt sizing (all unit-tested via plan_tpu_attempt):
TPU_PROBE_CAP_S = 90.0    # jax import ~15 s + tunneled device init; a
                          # healthy init is well under, a dead tunnel hangs
                          # to the cap — 90 s is the cost of certainty
FLUSH_MARGIN_S = 25.0     # kept back so the final record always prints
MIN_TPU_ATTEMPT_S = 60.0  # below this a cold attempt cannot finish; skip


def plan_tpu_attempt(remaining_s: float, probe_tpu_devices: float):
    """Pure cap arithmetic for the TPU step attempt (VERDICT r4 #2c).

    Returns (cap_s, reason): cap_s == 0 means skip. With a live probe the
    attempt gets everything left minus the flush margin — the r4 design's
    fixed 330 s cap + 140 s e2e reserve starved the success path; on a live
    chip the headline measurement outranks the e2e reserve (which upgrades
    to TPU opportunistically afterwards anyway)."""
    if probe_tpu_devices <= 0:
        return 0.0, "liveness probe found no TPU"
    cap = remaining_s - FLUSH_MARGIN_S
    if cap < MIN_TPU_ATTEMPT_S:
        return 0.0, f"budget too thin for a TPU attempt ({remaining_s:.0f}s left)"
    return cap, "live"


def _run_child(mode: str, timeout_s: float, env_extra: dict | None = None):
    """Run `bench.py --child --mode <mode>` in a fresh process; return the
    last JSON-parsable stdout line, or an error string."""
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", "--mode", mode],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s:.0f}s"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed, None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)[-500:]


# Hard total budget for the whole orchestration (all children + sleeps).
# The driver's outer timeout is empirically <25 min; staying at 10 keeps a
# wide margin AND leaves the provisional line on stdout within the first
# minute regardless.
BENCH_TOTAL_BUDGET_S = 600.0

# MOCO_TPU_FORCE_CPU (not JAX_PLATFORMS): the sandbox sitecustomize
# force-registers the axon TPU platform and overrides the env var, so the
# child must switch platforms IN-PROCESS via jax.config
_CPU_ENV = {"MOCO_TPU_FORCE_CPU": "1"}


class _Orchestrator:
    """Budget-tracked child runner that always has a printable record.

    Measured child costs on the 1-core sandbox (2026-07-30): step proxy
    ~45 s, input ~11 s, e2e proxy ~45 s — the full CPU sweep is ~100 s,
    so most of the budget funds the TPU attempt.
    """

    def __init__(self, mode: str, budget_s: float):
        self.mode = mode
        self.deadline = time.monotonic() + budget_s
        self.errors: list[str] = []
        self.best: dict | None = None  # headline record for `mode`
        self.extras: dict = {}         # folded "input"/"e2e" summaries
        self.last_timed_out = False    # structured hang-vs-failure signal

    def remaining(self) -> float:
        return self.deadline - time.monotonic()

    def run(self, name: str, mode: str, cap_s: float, env: dict | None):
        """One child attempt, capped by both `cap_s` and the global budget."""
        timeout_s = min(cap_s, self.remaining())
        if timeout_s < 5.0:
            self.errors.append(f"{name}: skipped, budget exhausted")
            return None
        result, err = _run_child(mode, timeout_s, env)
        if result is None:
            # classified here, from _run_child's structured outcome — child
            # stderr containing the word "timeout" must not masquerade as a
            # hang, so never grep the error strings for this
            self.last_timed_out = err.startswith("timeout after")
            self.errors.append(f"{name}: {err}")
        return result

    def record(self) -> dict:
        if self.best is not None:
            rec = dict(self.best)
        else:
            metric, unit = BENCH_FALLBACK_METRICS[self.mode]
            rec = {"metric": metric, "value": 0.0, "unit": unit,
                   "vs_baseline": 0.0}
        rec.update(self.extras)
        if self.errors:
            rec["degraded_from"] = self.errors[-8:]
        return rec

    def flush(self) -> None:
        print(json.dumps(self.record()), flush=True)


def orchestrate(mode: str) -> dict:
    """Cheap-first, budget-bounded measurement. Never raises, never exits
    non-zero, always leaves at least one metric-bearing JSON line on stdout
    (consumers take the LAST one). Returns the final consolidated record
    (the `--gate` caller feeds it to tools/bench_gate.py)."""
    try:
        budget = float(os.environ.get("MOCO_TPU_BENCH_BUDGET_S",
                                      BENCH_TOTAL_BUDGET_S))
    except ValueError:  # a malformed override must not kill the bench
        budget = BENCH_TOTAL_BUDGET_S
    orch = _Orchestrator(mode, budget)

    def _flush_and_exit(signum, frame):  # SIGTERM/SIGINT: save the evidence
        orch.errors.append(f"interrupted by signal {signum}")
        orch.flush()
        os._exit(0)

    prev_handlers = {
        sig: signal.signal(sig, _flush_and_exit)
        for sig in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        _orchestrate_body(mode, orch)
    finally:
        # restore the callers' dispositions: an in-process caller (tests,
        # embedding drivers) must not inherit a handler that os._exit(0)s
        # their process on the next Ctrl-C
        for sig, prev in prev_handlers.items():
            signal.signal(sig, prev)
    return orch.record()


def _orchestrate_body(mode: str, orch: "_Orchestrator") -> None:
    if mode == "input":  # never needs an accelerator
        orch.best = orch.run("cpu", "input", 300.0, _CPU_ENV)
        orch.flush()
        return
    if mode == "serve":  # ISSUE 5: warm-bucket serving latency (CPU proxy)
        # ISSUE 10: the explicit --mode serve run (300 s) also measures
        # the FLEET rows (rps vs replica count through the router, kill
        # drill). The step-mode serve child below keeps its tight 90 s
        # cap and skips them — three replica boots don't fit there.
        orch.best = orch.run("cpu", "serve", 300.0,
                             {**_CPU_ENV, "MOCO_TPU_BENCH_FLEET": "1"})
        orch.flush()
        return

    # 1) guaranteed number first: the CPU proxy, printed immediately as a
    #    provisional record so an external SIGKILL cannot erase everything
    orch.best = orch.run("cpu-proxy", mode, 180.0, _CPU_ENV)
    if orch.best is not None:
        orch.flush()

    # 2) cheap input-path summary (VERDICT r3 #8) while the budget is fat
    if mode == "step" and orch.remaining() > 300.0:
        inp = orch.run("input", "input", 90.0, _CPU_ENV)
        if inp is not None:
            orch.extras["input"] = {k: inp[k] for k in
                                    ("value", "unit", "detail",
                                     "cores_per_8x1650imgs_chip_host")
                                    if k in inp}

    # 3) liveness probe: a cheap `jax.devices()` child decides whether any
    #    expensive attempt is worth making (VERDICT r4 #2b). A dead tunnel
    #    hangs the probe to its 90 s cap — still 4x cheaper than hanging
    #    the full attempt, and it buys the live path a far bigger window
    probe = orch.run("tpu-probe", "probe", TPU_PROBE_CAP_S, {})
    probe_devices = float(probe["value"]) if probe is not None else 0.0
    cap, reason = plan_tpu_attempt(orch.remaining(), probe_devices)

    # 4) the real target: TPU attempt with everything the probe left us
    tpu = None
    if cap > 0:
        tpu = orch.run("tpu", mode, cap, {})
        if tpu is None and not orch.last_timed_out:
            # a fast rc!=0 may be a Pallas/Mosaic compile rejection —
            # MOCO_TPU_DISABLE_PALLAS rules the custom-kernel path out
            # (fused_bn_conv is already OFF by default, so DISABLE_FUSED
            # would be a no-op here — ADVICE r4). A timeout on a LIVE chip
            # means the compile didn't fit: retrying recompiles from
            # scratch and times out again, so never retry a hang
            retry_cap, _ = plan_tpu_attempt(orch.remaining() - 10.0,
                                            probe_devices)
            if retry_cap > 0:
                time.sleep(10.0)
                tpu = orch.run("tpu-retry", mode, retry_cap,
                               {"MOCO_TPU_DISABLE_PALLAS": "1"})
    else:
        orch.errors.append(f"tpu: skipped ({reason})")
    if tpu is not None:
        orch.best = tpu

    # 5) e2e summary: on TPU only if the TPU step just worked, else the CPU
    #    proxy (the axon relay can hang — never probe it twice on a dead
    #    day). On a live day the step child may rightfully have consumed
    #    the reserve; the omission is recorded rather than starving step
    if mode == "step":
        if orch.remaining() > 120.0:
            e2e_env = None if tpu is not None else _CPU_ENV
            e2e = orch.run("e2e", "e2e", orch.remaining() - 15.0, e2e_env)
            if e2e is not None:
                orch.extras["e2e"] = {k: e2e[k] for k in
                                      ("metric", "value", "unit",
                                       "vs_baseline", "input_pipeline",
                                       # ISSUE 14: the service + prestage
                                       # rows and their shared ceiling
                                       "service", "prestage",
                                       "device_bound_imgs_per_sec_per_chip")
                                      if k in e2e}
        else:
            orch.errors.append("e2e: skipped, step attempt consumed the budget")

    # 6) serving-path trajectory row (ISSUE 5): the tiny-model full-stack
    #    latency/occupancy record (bench_serve), folded like input's. LAST
    #    on purpose: on a tight day the headline step/e2e measurements
    #    outrank it, and its CPU child is cheap when the budget is fat
    if mode == "step" and orch.remaining() > 60.0:
        srv = orch.run("serve", "serve", 90.0, _CPU_ENV)
        if srv is not None:
            orch.extras["serve"] = {k: srv[k] for k in
                                    ("metric", "value", "unit", "detail")
                                    if k in srv}

    orch.flush()


import numpy as np

BASELINE_IMGS_PER_SEC_PER_CHIP = 168.0  # 8xV100 MoCo-v2, BASELINE.md


def bench_probe():
    """Liveness child: import jax + list devices, nothing else. Cheap on a
    live day; the ONLY thing that hangs (to its small cap) on a dead one."""
    import jax

    t0 = time.perf_counter()
    devs = jax.devices()
    print(json.dumps({
        "metric": "tpu_liveness",
        "value": float(sum(d.platform == "tpu" for d in devs)),
        "unit": "devices",
        "vs_baseline": 0.0,
        "platform": devs[0].platform if devs else "none",
        "init_s": round(time.perf_counter() - t0, 1),
    }))


def _make_jpeg_tree(root, n_images: int = 256, classes: int = 4, size=(500, 375)):
    """ImageNet-shaped synthetic JPEGs (4:3, quality 85, ~30-60 KB)."""
    import os

    from PIL import Image

    rng = np.random.RandomState(0)
    paths = []
    for c in range(classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(n_images // classes):
            # low-frequency content + noise: realistic JPEG entropy, cheap
            base = rng.randint(0, 256, (6, 8, 3)).astype(np.uint8)
            img = np.asarray(
                Image.fromarray(base).resize(size, Image.BILINEAR), np.uint8
            )
            img = np.clip(
                img.astype(np.int16) + rng.randint(-25, 25, img.shape[:2] + (1,)),
                0, 255,
            ).astype(np.uint8)
            p = os.path.join(d, f"{i}.jpg")
            Image.fromarray(img).save(p, quality=85)
            paths.append(p)
    return paths


def _staged_scaling_rows(root: str, detail: dict) -> None:
    """ISSUE 3 acceptance rows: END-TO-END staging throughput (decode →
    pooled canvas → device transfer) through the real `epoch_loader` at
    1/2/4 staging workers, native pool sized to match. Best-of-3 per row:
    these rows judge CAPACITY scaling, and the monotone 1→4 criterion must
    not be decided by a scheduler hiccup in one rep (the first rep also
    absorbs the one-time canvas page-fault, the r4 artifact)."""
    from moco_tpu.data.datasets import ImageFolder
    from moco_tpu.data.loader import epoch_loader
    from moco_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(1)
    bs = 64
    for w in (1, 2, 4):
        folder = ImageFolder(root, num_workers=w)
        rates = []
        for rep in range(3):
            loader = epoch_loader(folder, epoch=rep, seed=0, global_batch=bs,
                                  mesh=mesh, workers=w, depth=2)
            try:
                t0 = time.perf_counter()
                n = 0
                for _batch in loader:
                    n += bs
                rates.append(n / (time.perf_counter() - t0))
            finally:
                loader.close_quietly()
        detail[f"staged_s512_w{w}"] = round(max(rates), 1)


def bench_input():
    """Host staging throughput: native loader by thread count + PIL, plus
    the ISSUE 3 `staged_s512_w{1,2,4}` end-to-end staging scaling rows."""
    import tempfile

    from moco_tpu.data.datasets import ImageFolder
    from moco_tpu.data.native_loader import NativeStagingLoader

    root = tempfile.mkdtemp(prefix="bench_jpeg_")
    paths = _make_jpeg_tree(root)
    ncpu = os.cpu_count() or 1
    detail = {}
    best = 0.0
    try:
        # both canvases: 256 (r2 default) and 512 (the full-resolution
        # default — typical ImageNet photos stage pixel-exact, VERDICT r2
        # #4's measured-cost requirement)
        for stage in (256, 512):
            for threads in sorted({1, 2, 4, max(1, ncpu)}):
                loader = NativeStagingLoader(stage, stage * 2, threads)
                # FULL-SIZE warm pass: thread-pool startup plus the first
                # page-faulting allocation of the whole staging canvas
                # (~400 MB at s512) must land outside the timed region —
                # r4's single-shot timing put that one-time cost inside the
                # first config measured, which is exactly the physically
                # impossible "superlinear 1t→2t" artifact in BENCH_r04
                # (VERDICT r4 weak #2 / #4)
                _, _, failures = loader.load_batch(paths)
                assert failures == 0
                reps = []
                for _ in range(3):  # median-of-3: robust on a shared core
                    t0 = time.perf_counter()
                    _, _, failures = loader.load_batch(paths)
                    dt = time.perf_counter() - t0
                    assert failures == 0
                    reps.append(len(paths) / dt)
                rate = sorted(reps)[1]
                detail[f"native_s{stage}_{threads}t"] = round(rate, 1)
                if stage == 512:  # headline = the shipping default
                    best = max(best, rate)
    except RuntimeError as e:
        # no native toolchain on this host: report the PIL path alone,
        # mirroring ImageFolder's backend="auto" degradation
        detail["native_unavailable"] = str(e)
    folder = ImageFolder(root, backend="pil", num_workers=1)  # default 512
    sub = np.arange(min(64, len(folder)))
    folder.get_batch(sub[:8])
    t0 = time.perf_counter()
    folder.get_batch(sub)
    detail["pil_s512_1w"] = round(len(sub) / (time.perf_counter() - t0), 1)
    best = max(best, detail["pil_s512_1w"])
    _staged_scaling_rows(root, detail)
    # the input-path question (SURVEY §7 hard-part 4): one 8-chip host must
    # stage ~8*step_rate imgs/s; report how many of THESE cores that takes
    per_core = detail.get("native_s512_1t", detail["pil_s512_1w"])
    print(
        json.dumps(
            {
                "metric": "host_staging_throughput",
                "value": round(best, 1),
                "unit": "imgs/sec",
                "vs_baseline": round(best / (8 * BASELINE_IMGS_PER_SEC_PER_CHIP), 3),
                "detail": detail,
                "cores_on_this_host": ncpu,
                "cores_per_8x1650imgs_chip_host": round(8 * 1650 / per_core, 1),
            }
        )
    )


def bench_e2e():
    """Input-fed training: epoch_loader + ImageFolder (JPEG decode in the
    loop) feeding the real MoCo-v2 step, through the ISSUE 3 pipeline:
    parallel sharded staging, decode-once canvas cache, staging-side
    (overlapped) H2D, and extent-trimmed transfers. The warm epoch fills
    the cache and compiles; the timed epoch then measures the shipped
    steady state — epochs >= 2 of a real run, where decode is a memcpy and
    the transfer hides under the step. The gap to the default (staged)
    metric is whatever input cost the overlap could NOT hide."""
    import tempfile

    import jax

    from moco_tpu.config import get_preset
    from moco_tpu.data.canvas_cache import CachedDataset
    from moco_tpu.data.datasets import ImageFolder
    from moco_tpu.data.loader import epoch_loader
    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.utils.benchkit import build_v2_fused_step

    devices = jax.devices()
    n_chips = len(devices)
    on_tpu = devices[0].platform == "tpu"
    mesh = create_mesh(n_chips)
    root = tempfile.mkdtemp(prefix="bench_e2e_")
    batch = (128 if on_tpu else 8) * n_chips
    n_images = batch * 4
    _make_jpeg_tree(root, n_images=n_images)
    # TPU: the shipping full-resolution default (512 canvas); CPU proxy
    # keeps the smaller canvas so the tiny-model proxy stays fast
    stage_size = 0 if on_tpu else 256
    if on_tpu:
        config = get_preset("imagenet-moco-v2").replace(batch_size=batch)
        if os.environ.get("MOCO_TPU_DISABLE_FUSED"):
            config = config.replace(fused_bn_conv=False)
        steps = 6
    else:
        config = get_preset("imagenet-moco-v2").replace(
            arch="resnet_tiny", cifar_stem=True, compute_dtype="float32",
            image_size=32, batch_size=batch, num_negatives=64 * n_chips,
            embed_dim=32,
        )
        steps = 3
    workers = max(1, min(4, os.cpu_count() or 1))
    depth = config.prefetch_depth
    inner = ImageFolder(root, **({"stage_size": stage_size} if stage_size else {}))
    # cache sized to hold the whole tree (+25% slack): the timed epoch is
    # then the decode-once steady state
    cache_mb = max(
        64, int(n_images * inner.stage_h * inner.stage_w * 3 * 1.25 / 2**20)
    )
    dataset = CachedDataset(inner, cache_mb)
    fused, state = build_v2_fused_step(config, mesh)

    def drive_loader(loader, max_steps):
        nonlocal state
        n = 0
        metrics = None
        try:
            for imgs, _labels, extents in loader:
                state, metrics = fused(state, imgs, extents, n)
                n += 1
                if n >= max_steps:
                    break
        finally:
            # quietly: bench's child-attempt contract is that measurement
            # orchestration never raises, and the max_steps break makes a
            # stale staged-read error possible even on success
            loader.close_quietly()
        if metrics is None:
            raise RuntimeError(
                f"loader yielded zero batches (batch {batch}, "
                f"{len(dataset)} images)")
        loss = float(metrics["loss"])  # d2h sync (block_until_ready lies on the relay)
        assert np.isfinite(loss), f"non-finite e2e loss {loss}"
        return n

    def run_epoch(epoch, max_steps, ds=None, trim=True):
        loader = epoch_loader(ds if ds is not None else dataset, epoch, 0,
                              batch, mesh, workers=workers, depth=depth,
                              trim_h2d=trim)
        try:
            return drive_loader(loader, max_steps)
        finally:
            loader.close_quietly()  # idempotent: drive_loader closed it

    t_c = time.perf_counter()
    # warm a FULL epoch: compiles the (one, trimmed) step shape AND fills
    # the decode-once cache, so the timed epoch measures steady state
    run_epoch(0, n_images // batch)
    compile_warmup_s = time.perf_counter() - t_c
    t0 = time.perf_counter()
    n = run_epoch(1, steps)
    dt = time.perf_counter() - t0
    per_chip = batch * n / dt / n_chips
    lookups = dataset.hits + dataset.misses
    record = {
        "metric": "moco_v2_r50_e2e_input_fed_throughput_per_chip"
        if on_tpu
        else "moco_v2_tiny_cpu_e2e_proxy_per_chip",
        "value": round(per_chip, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
        # evidence for sizing the TPU window (VERDICT r4 #2): how
        # long compile+warmup actually took on THIS backend
        "compile_warmup_s": round(compile_warmup_s, 1),
        # the ISSUE 3 pipeline shape this number was measured with
        "input_pipeline": {
            "staging_workers": workers,
            "prefetch_depth": depth,
            "input_cache_mb": cache_mb,
            "h2d_trim": True,
            "cache_hit_rate": round(dataset.hits / lookups, 3)
            if lookups else 0.0,
        },
    }
    # provisional line FIRST (the orchestrate() convention — consumers
    # take the LAST json line): the measured headline must survive a
    # budget kill anywhere in the probe/service/prestage rows below
    # (the device-bound probe compiles a NEW untrimmed shape on TPU)
    print(json.dumps(record), flush=True)
    # device-bound step rate: the same fused step over one ALREADY-STAGED
    # batch — the ceiling any input pipeline is chasing (the prestage
    # acceptance bar is 0.9x of THIS, measured in the same round)
    device_bound = None
    staged = d_imgs = d_exts = None
    try:
        staged = []
        loader = epoch_loader(dataset, 2, 0, batch, mesh, workers=workers,
                              depth=depth, trim_h2d=False)
        try:
            for item in loader:
                staged.append(item)
                break
        finally:
            loader.close_quietly()
        d_imgs, _d_labels, d_exts = staged[0]
        # thread `state` through: the fused step DONATES its input state,
        # so a copy under another name would leave `state` a deleted
        # buffer for the service/prestage rows that run after this
        state, m = fused(state, d_imgs, d_exts, 0)  # compile
        float(m["loss"])
        t0 = time.perf_counter()
        for i in range(steps):
            state, m = fused(state, d_imgs, d_exts, i)
        float(m["loss"])
        db_dt = time.perf_counter() - t0
        device_bound = batch * steps / db_dt / n_chips
        record["device_bound_imgs_per_sec_per_chip"] = round(device_bound, 2)
    except Exception as e:  # noqa: BLE001 — a failed row must not void the headline
        record["device_bound_error"] = f"{type(e).__name__}: {e}"
    finally:
        # release the probe batch EVEN when the probe failed: a full
        # per-host canvas batch pinned in HBM would add pressure to the
        # service/prestage rows measured next
        staged = d_imgs = d_exts = None  # noqa: F841
    print(json.dumps(record), flush=True)  # headline + device-bound row
    record["service"] = _bench_e2e_service(
        root, stage_size, cache_mb, len(dataset), batch, mesh, n_chips,
        on_tpu, depth, workers, steps, n_images, drive_loader)
    record["prestage"] = _bench_e2e_prestage(
        inner, batch, n_chips, on_tpu, steps, n_images, device_bound,
        run_epoch)
    print(json.dumps(record), flush=True)


def _bench_e2e_service(root, stage_size, cache_mb, dataset_len, batch,
                       mesh, n_chips, on_tpu, depth, workers, steps,
                       n_images, drive_loader) -> dict:
    """The disaggregated-service e2e row (ISSUE 14): the SAME fused step
    fed by a ServiceClient over 2 real LocalServerPool staging servers
    (stdlib supervisor + decode-worker subprocess each) on this host. A
    warm epoch fills the server-side decode-once caches and compiles the
    untrimmed canvas shape; the timed epoch is the service steady state.
    Never raises — a dead pool reports {"error": ...} and the in-process
    headline stands."""
    import shutil as _shutil
    import tempfile as _tempfile

    out: dict = {
        "metric": "moco_v2_r50_e2e_service_throughput_per_chip"
        if on_tpu else "moco_v2_tiny_cpu_e2e_service_proxy_per_chip",
        "unit": "imgs/sec/chip",
        "servers": 2,
    }
    svc_root = ""
    pool = None
    try:
        # everything inside the try: the docstring's never-raises
        # contract covers construction too (health-port bind, tracer
        # dirs) AND the moco_tpu imports — a stripped deployment must
        # degrade to an {"error": ...} row, not skip the prestage row
        # and the consolidated record
        from moco_tpu.data.service.client import service_epoch_loader
        from moco_tpu.data.service.fleet import LocalServerPool

        svc_root = _tempfile.mkdtemp(prefix="bench_svc_")
        worker_args = ["--dataset", "imagefolder", "--data-dir", root,
                       "--cache-mb", str(cache_mb)]
        if stage_size:
            worker_args += ["--stage-size", str(stage_size)]
        pool = LocalServerPool(2, worker_args, telemetry_root=svc_root)
        pool.start()
        if not pool.wait_healthy(90.0):
            raise RuntimeError("staging-server pool never became healthy")

        def run_service_epoch(epoch, max_steps):
            loader = service_epoch_loader(
                pool.endpoints_spec(), dataset_len, epoch, 0, batch,
                mesh, depth=depth, streams=workers)
            try:
                return drive_loader(loader, max_steps)
            finally:
                loader.close_quietly()  # idempotent: drive_loader closed it

        run_service_epoch(0, n_images // batch)  # warm: caches + compile
        t0 = time.perf_counter()
        n = run_service_epoch(1, steps)
        dt = time.perf_counter() - t0
        per_chip = batch * n / dt / n_chips
        out["value"] = round(per_chip, 2)
        out["vs_baseline"] = round(
            per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3)
        # per-server rows (noisy detail — bench_gate excludes them the
        # way it excludes per-thread input rows). A LIVE pong snapshot,
        # not the supervisor's cached probe: the timed epoch fits inside
        # one probe period, so the cache still shows the pre-shard zeros
        from moco_tpu.data.service import protocol as _protocol

        detail = {}
        for server in pool.servers:
            stats = (_protocol.ping(server.host, server.data_port,
                                    timeout_s=5.0)
                     or server.stats().get("worker_stats", {}))
            sid = server.server_id
            for key in ("shards", "streamed_mb", "shard_s_p50",
                        "shard_s_p95", "cache_hit_rate"):
                if key in stats:
                    detail[f"server{sid}_{key}"] = stats[key]
        out["detail"] = detail
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        if pool is not None:
            pool.close_quietly()
        # the record already captured the per-server detail: the
        # telemetry dirs + worker logs must not accumulate in /tmp
        # across gate runs (the prestage sibling's rmtree discipline)
        if svc_root:
            _shutil.rmtree(svc_root, ignore_errors=True)
    return out


def _bench_e2e_prestage(inner, batch, n_chips, on_tpu, steps, n_images,
                        device_bound, run_epoch) -> dict:
    """The pre-staged epoch-cache e2e row (ISSUE 14): decode the whole
    tree ONCE into the mmap prestage format, then run the same fused
    step over a PrestagedDataset — a hit epoch is row gathers at memcpy
    speed, so this row is expected to sit within 0.9x of the
    device-bound step rate (the ISSUE acceptance bar, recorded as
    `vs_device_bound`). Never raises."""
    import shutil as _shutil
    import tempfile as _tempfile

    out: dict = {
        "metric": "moco_v2_r50_e2e_prestage_throughput_per_chip"
        if on_tpu else "moco_v2_tiny_cpu_e2e_prestage_proxy_per_chip",
        "unit": "imgs/sec/chip",
    }
    pre_root = _tempfile.mkdtemp(prefix="bench_prestage_")
    try:
        # imports inside the try: never-raises covers a stripped
        # deployment too — degrade to the {"error": ...} row
        from moco_tpu.data.service.prestage import (
            PrestagedDataset,
            write_prestage,
        )

        t0 = time.perf_counter()
        write_prestage(inner, pre_root)
        out["prestage_write_s"] = round(time.perf_counter() - t0, 1)
        pre = PrestagedDataset(pre_root)
        # trim=False: the device-bound ceiling this row is ratioed
        # against (and the service row) runs the UNTRIMMED step shape —
        # a trimmed epoch would inflate vs_device_bound by comparing a
        # cheaper compiled program against the full-canvas one
        run_epoch(0, n_images // batch, ds=pre, trim=False)  # warm mmap
        t0 = time.perf_counter()
        n = run_epoch(1, steps, ds=pre, trim=False)
        dt = time.perf_counter() - t0
        per_chip = batch * n / dt / n_chips
        out["value"] = round(per_chip, 2)
        out["vs_baseline"] = round(
            per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3)
        if device_bound:
            out["device_bound"] = round(device_bound, 2)
            out["vs_device_bound"] = round(per_chip / device_bound, 3)
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        _shutil.rmtree(pre_root, ignore_errors=True)
    return out


def bench_serve():
    """Warm-bucket serving percentiles (ISSUE 5): the FULL serving stack —
    stdlib HTTP front end, micro-batcher, bucketed-compile engine — under
    the closed-loop generator (tools/serve_bench.run_load) at fixed
    concurrency, on the tiny CPU proxy model. Every bucket is compiled at
    warmup, so the record measures steady-state batching, not compiles;
    the trajectory row to watch is p95 vs the deadline knob and mean
    batch occupancy at this concurrency."""
    import jax
    import jax.numpy as jnp

    from moco_tpu.models import build_backbone
    from moco_tpu.serve import EmbeddingEngine, EmbedService, ServeFrontend

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "tools"))
    import serve_bench

    concurrency, total = 32, 512
    deadline_ms = 5000.0
    model = build_backbone("resnet_tiny", cifar_stem=True)
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False
    )
    engine = EmbeddingEngine(
        model, variables["params"], variables.get("batch_stats", {}),
        image_size=32, buckets=(1, 8, 32),
    )
    t0 = time.perf_counter()
    service = EmbedService(
        engine, flush_ms=5.0, max_queue=128,
        request_deadline_ms=deadline_ms, cache_mb=0,
    )
    warmup_s = time.perf_counter() - t0
    frontend = ServeFrontend(service, port=0)
    frontend.start()
    try:
        summary = serve_bench.run_load(
            frontend.url, concurrency=concurrency, total_requests=total,
            image_size=32, pool=64, timeout_s=30.0,
        )
        stats = service.stats()
    finally:
        service.drain()
        frontend.shutdown()
    assert summary["lost"] == 0, f"lost requests: {summary['lost_detail']}"
    detail = {
        "concurrency": concurrency,
        "requests": total,
        "throughput_rps": summary["throughput_rps"],
        "latency_ms": summary["latency_ms"],
        "shed": summary["shed"],
        "batches": stats["batches"],
        "occupancy_mean": stats["occupancy_mean"],
        "buckets": stats["buckets"],
    }
    if os.environ.get("MOCO_TPU_BENCH_FLEET"):
        detail["fleet"] = _bench_serve_fleet(variables, serve_bench)
    print(
        json.dumps(
            {
                "metric": "serve_tiny_cpu_embed_p95_latency_ms",
                "value": summary["latency_ms"]["p95"],
                "unit": "ms",
                "vs_baseline": 0.0,
                "compile_warmup_s": round(warmup_s, 1),
                "detail": detail,
            }
        )
    )


def _bench_serve_fleet(variables, serve_bench) -> dict:
    """Fleet rows (ISSUE 10): rps/p99/lost vs replica count through
    tools/serve_fleet.py + real tools/serve.py replicas on the tiny
    export, closed loop with a kill drill at 2 replicas. Each replica is
    a full cold serve.py boot (jax import + ladder compile), so the rows
    run only under the 300 s `--mode serve` child (MOCO_TPU_BENCH_FLEET
    gates them). Failures degrade to an error field, never kill the
    headline record."""
    import tempfile

    import jax

    from moco_tpu.checkpoint import _save_flat, resnet_to_torchvision

    import shutil

    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        export = os.path.join(tmp, "tiny.npz")
        flat = resnet_to_torchvision(
            jax.tree.map(np.asarray, variables["params"]),
            jax.tree.map(np.asarray, variables.get("batch_stats", {})),
            prefix="module.encoder_q.",
        )
        _save_flat(flat, export)
        repo = os.path.dirname(os.path.abspath(__file__))
        replica_cmd = [
            sys.executable, os.path.join(repo, "tools", "serve.py"),
            "--pretrained", export, "--arch", "resnet_tiny",
            "--image-size", "32", "--cifar-stem", "true",
            "--buckets", "1", "8", "32", "--flush-ms", "5.0",
            "--max-queue", "128",
        ]
        env = dict(os.environ)
        env.setdefault("MOCO_TPU_NO_CACHE", "1")  # throwaway replicas
        rows = serve_bench.run_fleet_bench(
            replica_cmd, counts=(1, 2), concurrency=32,
            total_requests=256, image_size=32, pool=32, timeout_s=30.0,
            kill_drill=True, kill_after_s=0.5, boot_timeout_s=120.0,
            env=env,
        )
        return {"rows": rows,
                "lost_total": sum(r.get("lost", 0) for r in rows)}
    except Exception as e:  # the fleet rows are a bonus, never the record
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# wall-clock cap for the per-mode grad-sync sweep inside the step child
# (ISSUE 6): 3 extra tiny-model compiles on CPU fit comfortably; on a slow
# day the sweep degrades to whichever modes finished, never eats the
# headline's budget
GRADSYNC_SWEEP_CAP_S = 150.0

# same contract for the per-sharding-mode v3 sweep (ISSUE 15)
SHARDING_SWEEP_CAP_S = 150.0


def _sharding_sweep(mesh, n_chips: int, on_tpu: bool) -> dict:
    """imgs/s + synced step percentiles + per-device state bytes per
    `sharding` mode on the SAME v3 config (ISSUE 15 satellite) — the
    trajectory rows that show what FSDP costs in step time and buys in
    per-device footprint on this backend. Per-mode error isolation and a
    wall-clock budget, exactly like the grad_sync sweep: a broken mode
    costs only its own row. Peak HBM rides along where the backend's
    allocator reports it (DeviceMonitor; absent on CPU)."""
    from moco_tpu.config import get_preset
    from moco_tpu.parallel.fsdp import state_bytes_per_device
    from moco_tpu.parallel.mesh import mesh_for_config
    from moco_tpu.telemetry.device import DeviceMonitor
    from moco_tpu.utils.benchkit import (
        build_v2_fused_bench,
        time_step_percentiles,
    )

    if on_tpu:
        base = get_preset("imagenet-moco-v3-vits").replace(
            batch_size=64 * n_chips, dataset="synthetic", remat=True)
        warm, steps = 2, 4
    else:  # CPU proxy: the tiny ViT (width 64, depth 2) keeps the three
        # extra compiles inside the sweep budget
        base = get_preset("imagenet-moco-v3-vits").replace(
            arch="vit_tiny", compute_dtype="float32", image_size=32,
            batch_size=8 * n_chips, embed_dim=32, dataset="synthetic",
            warmup_epochs=0, lr=1e-3, base_lr=0.0)
        warm, steps = 2, 3
    modes = ["dp"]
    if n_chips >= 2:
        modes.append("fsdp")
    if n_chips >= 4:
        modes.append("fsdp_tp")
    detail = {}
    deadline = time.monotonic() + float(
        os.environ.get("MOCO_TPU_BENCH_SHARDING_S", SHARDING_SWEEP_CAP_S))
    for mode in modes:
        if time.monotonic() > deadline:
            detail[mode] = {"skipped": "sweep budget exhausted"}
            continue
        try:
            cfg = base.replace(sharding=mode)
            m_mode = mesh_for_config(cfg, mesh)
            fused, state, imgs_u8, extents = build_v2_fused_bench(cfg, m_mode)
            m = None
            for w in range(warm):
                state, m = fused(state, imgs_u8, extents, w)
            assert np.isfinite(float(m["loss"])), f"non-finite {mode} loss"
            pcts, state = time_step_percentiles(
                fused, state, imgs_u8, extents, steps=steps)
            row = {
                "imgs_per_sec_per_chip": round(
                    cfg.batch_size / (pcts["p50"] / 1e3) / n_chips, 2),
                "step_time_synced_ms": pcts,
                **state_bytes_per_device(state),
            }
            hbm = DeviceMonitor().sample()
            if "hbm_peak_bytes" in hbm:
                row["hbm_peak_bytes"] = hbm["hbm_peak_bytes"]
            detail[mode] = row
        except Exception as e:  # noqa: BLE001 — degraded row, never fatal
            detail[mode] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return detail


def _grad_sync_sweep(config, mesh, n_chips: int, fused_pcts: dict) -> dict:
    """imgs/s + synced step-time percentiles per grad_sync mode on the SAME
    config (ISSUE 6 satellite) — the trajectory row that shows whether
    bucketing/quantization/sparsification actually buys step time on this
    backend. `fused` reuses the headline child's own PERCENTILE pass (same
    program, same per-step-synced timing basis as the rows below — the
    chained best-of-rounds headline mean pays no per-step sync and would
    make fused look faster than every other mode by measurement artifact
    alone; on the relay each synced sample carries ~70 ms of round-trip)."""
    from moco_tpu.parallel.gradsync import GradSync
    from moco_tpu.utils.benchkit import build_v2_fused_bench, time_step_percentiles

    detail = {"fused": {
        "imgs_per_sec_per_chip": round(
            config.batch_size / (fused_pcts["p50"] / 1e3) / n_chips, 2),
        "step_time_synced_ms": dict(fused_pcts),
    }}
    deadline = time.monotonic() + float(
        os.environ.get("MOCO_TPU_BENCH_GRADSYNC_S", GRADSYNC_SWEEP_CAP_S))
    for gs_mode in ("bucketed", "quantized", "demo"):
        if time.monotonic() > deadline:
            detail[gs_mode] = {"skipped": "sweep budget exhausted"}
            continue
        # per-mode isolation: a broken mode must cost ONLY its own row —
        # the headline record (and the other rows) always print
        try:
            cfg = config.replace(grad_sync=gs_mode)
            if gs_mode == "demo":
                cfg = cfg.replace(grad_sync_cadence=4, grad_sync_topk=0.01)
            fused, state, imgs_u8, extents = build_v2_fused_bench(cfg, mesh)
            # two warm steps (compile + first-donation round), then a short
            # synced percentile pass — one warm step leaves a seconds-scale
            # warmup sample inside the percentiles (measured r6)
            m = None
            for w in range(2):
                state, m = fused(state, imgs_u8, extents, w)
            assert np.isfinite(float(m["loss"])), f"non-finite {gs_mode} loss"
            pcts, state = time_step_percentiles(
                fused, state, imgs_u8, extents, steps=4)
            gs = GradSync(cfg, n_chips)
            detail[gs_mode] = {
                "imgs_per_sec_per_chip": round(
                    cfg.batch_size / (pcts["p50"] / 1e3) / n_chips, 2),
                "step_time_synced_ms": pcts,
                "sync_bytes_per_step": gs.describe(state.params_q)[
                    "sync_bytes_per_step"],
            }
        except Exception as e:  # noqa: BLE001 — degraded row, never fatal
            detail[gs_mode] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return detail


def _telemetry_overhead_row(step_p50_ms: float, steps: int = 2000) -> dict:
    """Span-layer overhead evidence (ISSUE 8 acceptance): per-step cost of
    `trace_mode=steps` vs `off`, measured through the REAL per-step path
    (record_step + capture tick, ring flushes landing on a real spans
    file) and expressed as a share of this box's measured p50 step time.
    Simulated phases, real I/O: the span layer's cost is pure host work
    independent of what the device was doing, and 2000 iterations give a
    stable per-step number where re-timing two short train loops on a
    noisy 1-core box does not."""
    import shutil
    import tempfile

    from moco_tpu.telemetry.trace import Tracer

    phases = {"step_s": step_p50_ms / 1e3, "data_s": 1e-4, "host_s": 1e-4}
    per_step_ms = {}
    for mode in ("off", "steps"):
        tmp = tempfile.mkdtemp(prefix=f"trace_bench_{mode}_")
        try:
            tracer = Tracer(tmp, mode, proc="bench")
            t0 = time.perf_counter()
            for step in range(steps):
                tracer.record_step(step, phases)
                tracer.tick(step)
            tracer.close()
            per_step_ms[mode] = (time.perf_counter() - t0) / steps * 1e3
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    overhead_ms = max(per_step_ms["steps"] - per_step_ms["off"], 0.0)
    return {
        "per_step_ms": {k: round(v, 6) for k, v in per_step_ms.items()},
        "overhead_ms_per_step": round(overhead_ms, 6),
        "overhead_pct_of_step_p50": round(
            100.0 * overhead_ms / step_p50_ms, 4) if step_p50_ms else 0.0,
    }


def _health_overhead_row(config, mesh, step_p50_ms: float) -> dict:
    """In-graph learning-health diagnostics cost (ISSUE 13 acceptance:
    amortized overhead < 1% of p50 step time at the default stride).
    Builds the SAME fused program with `health_stride=DEFAULT_STRIDE`
    and times one synced stride-covering window, splitting ON-stride
    samples (the cond's real diagnostics branch) from OFF-stride ones
    (the zero branch): the amortized per-step cost is the on-stride
    premium divided by the stride, expressed against the headline
    (diagnostics-off) p50 — the same "share of step time" basis as the
    telemetry_overhead row. The off-stride p50 doubles as evidence that
    the gated program's steady state matches the headline program."""
    from moco_tpu.telemetry import percentiles_ms
    from moco_tpu.telemetry.health import DEFAULT_STRIDE
    from moco_tpu.utils.benchkit import build_v2_fused_bench

    stride = DEFAULT_STRIDE
    try:
        cfg = config.replace(health_stride=stride)
        fused, state, imgs_u8, extents = build_v2_fused_bench(cfg, mesh)
        m = None
        for w in range(2):  # compile + first-donation round; state.step
            state, m = fused(state, imgs_u8, extents, w)  # is now 2
        assert np.isfinite(float(m["loss"])), "non-finite health-bench loss"
        times_on, times_off = [], []
        for i in range(3 * stride):
            t0 = time.perf_counter()
            state, metrics = fused(state, imgs_u8, extents, 2 + i)
            loss = float(metrics["loss"])  # the only reliable sync (relay)
            # the cond keys on state.step, which the warmup left at 2 + i
            (times_on if (2 + i) % stride == 0
             else times_off).append(time.perf_counter() - t0)
        assert np.isfinite(loss), f"non-finite health-bench loss {loss}"
        on_ms = percentiles_ms(times_on)["p50"]
        off_ms = percentiles_ms(times_off)["p50"]
        premium_ms = max(on_ms - off_ms, 0.0)
        amortized_ms = premium_ms / stride
        return {
            "stride": stride,
            "step_ms_on_stride_p50": round(on_ms, 3),
            "step_ms_off_stride_p50": round(off_ms, 3),
            "overhead_ms_per_step": round(amortized_ms, 6),
            "overhead_pct_of_step_p50": round(
                100.0 * amortized_ms / step_p50_ms, 4)
            if step_p50_ms else 0.0,
        }
    except Exception as e:  # noqa: BLE001 — degraded row, never fatal
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def main():
    import jax

    from moco_tpu.config import get_preset
    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.utils.benchkit import (
        build_v2_fused_bench,
        time_fused_step,
        time_step_percentiles,
    )

    devices = jax.devices()
    n_chips = len(devices)
    on_tpu = devices[0].platform == "tpu"
    mesh = create_mesh(n_chips)

    # per-chip batch 128 (vs the reference's 32/GPU) — TPU MXU wants batch
    if on_tpu:
        config = get_preset("imagenet-moco-v2").replace(
            batch_size=128 * n_chips, dataset="synthetic"
        )
        steps, warmup = 20, 10
        if os.environ.get("MOCO_TPU_DISABLE_FUSED"):
            # manual knob (fused_bn_conv already defaults OFF pending
            # tools/_fused_validate.py on a chip; the orchestrator's retry
            # uses MOCO_TPU_DISABLE_PALLAS, which the aug's blur kernel
            # reads — ADVICE r4)
            config = config.replace(fused_bn_conv=False)
    else:  # CPU fallback so the bench is runnable anywhere (tiny proxy)
        config = get_preset("imagenet-moco-v2").replace(
            arch="resnet_tiny", cifar_stem=True, compute_dtype="float32",
            image_size=32, batch_size=8 * n_chips, num_negatives=64 * n_chips,
            embed_dim=32, dataset="synthetic",
        )
        steps, warmup = 5, 2

    # aug in the compute dtype (bf16 on TPU) fused into ONE program with
    # the step via the SAME build_fused_step the train driver uses; the
    # assembly and timing semantics (relay-sync via float(loss), generous
    # warmup, best-of-rounds, finite-loss asserts) live in benchkit, shared
    # with tools/_tpu_validate.py and tools/_perf_ab.py
    fused, state, imgs_u8, extents = build_v2_fused_bench(config, mesh)
    best, compile_warmup_s, loss, state = time_fused_step(
        fused, state, imgs_u8, extents, warmup=warmup, steps=steps)
    # tail distribution (ISSUE 2): per-step-synced p50/p95/p99 — comparable
    # across BENCH_*.json rounds, NOT to the chained headline mean (each
    # sample pays one device→host sync; see benchkit.time_step_percentiles)
    step_pcts, state = time_step_percentiles(
        fused, state, imgs_u8, extents, steps=steps)

    imgs_per_sec = config.batch_size / best
    per_chip = imgs_per_sec / n_chips
    # per-mode gradient-sync comparison on the same config (ISSUE 6); the
    # headline above IS the fused row, so only the three comm-efficient
    # modes compile extra programs
    grad_sync_detail = _grad_sync_sweep(config, mesh, n_chips, step_pcts)
    # per-sharding-mode v3 comparison (ISSUE 15): dp/fsdp/fsdp_tp rows on
    # one v3 config — throughput, synced percentiles, per-device bytes
    sharding_detail = _sharding_sweep(mesh, n_chips, on_tpu)
    # span-layer overhead row (ISSUE 8 acceptance: trace_mode=steps must
    # cost well under 3% of step time vs off)
    telemetry_detail = _telemetry_overhead_row(step_pcts["p50"])
    # in-graph learning-health diagnostics row (ISSUE 13 acceptance:
    # amortized cost < 1% of step p50 at the default stride; bench_gate
    # enforces the absolute cap)
    health_detail = _health_overhead_row(config, mesh, step_pcts["p50"])
    print(
        json.dumps(
            {
                "metric": "moco_v2_r50_pretrain_throughput_per_chip"
                if on_tpu
                else "moco_v2_tiny_cpu_proxy_throughput_per_chip",
                "value": round(per_chip, 2),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
                "fused_bn_conv": bool(config.fused_bn_conv),
                "final_loss": round(loss, 4),
                "step_time_synced_ms": step_pcts,
                "grad_sync": grad_sync_detail,
                "sharding": sharding_detail,
                "telemetry_overhead": telemetry_detail,
                "health_overhead": health_detail,
                # measured cold/warm compile evidence (VERDICT r4 #2): on
                # the first healthy contact this records how much of the
                # window the compile ate; with the persistent cache warm it
                # collapses to relay warmup
                "compile_warmup_s": round(compile_warmup_s, 1),
            }
        )
    )


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--mode",
                        choices=["step", "input", "e2e", "probe", "serve"],
                        default="step")
    parser.add_argument(
        "--child", action="store_true",
        help="run the measurement in THIS process (no retry shell); the "
             "default entry orchestrates children with retry + degradation",
    )
    parser.add_argument(
        "--gate", action="store_true",
        help="after measuring, compare the final record against the "
             "committed BENCH_r*.json trajectory (tools/bench_gate.py) "
             "and exit 1 on regression — the opt-out-of-silent-drift "
             "mode for CI (the default entry stays never-nonzero)",
    )
    args = parser.parse_args()
    if not args.child:
        record = orchestrate(args.mode)
        if args.gate:
            from tools.bench_gate import (
                flatten,
                gate_record,
                load_trajectory_flats,
            )

            fresh, _ = flatten(record)
            verdict = gate_record(fresh, load_trajectory_flats())
            print(json.dumps({"bench_gate": {
                "regressions": verdict["regressions"],
                "compared": verdict["compared"],
                "new_metrics": verdict["new_metrics"],
            }}), flush=True)
            sys.exit(1 if verdict["regressions"] or not fresh else 0)
    else:
        if os.environ.get("MOCO_TPU_FORCE_CPU"):
            # in-process platform switch — the sitecustomize overrides
            # JAX_PLATFORMS, and the axon backend can hang device init
            from moco_tpu.parallel.mesh import force_cpu_devices

            force_cpu_devices(1)
        if args.mode == "probe":
            bench_probe()
        elif args.mode == "input":
            bench_input()
        elif args.mode == "serve":
            bench_serve()
        else:
            # persistent compile cache (VERDICT r4 #2a): first healthy
            # contact pays the compile, later children measure
            from moco_tpu.utils.cache import enable_persistent_cache

            enable_persistent_cache()
            if args.mode == "e2e":
                bench_e2e()
            else:
                main()
