"""North-star benchmark: MoCo-v2 ResNet-50 pretrain throughput (imgs/sec/chip).

Runs the REAL training step — on-device two-crop augmentation + both encoder
forwards + ShuffleBN collectives + InfoNCE + backward + SGD + donated queue
update — on whatever chips are present (the sandbox exposes one), with the
full 65536-slot queue and bf16 compute, and compares per-chip throughput
against the reference's 8xV100 number (BASELINE.md: ~1340 imgs/s global =
168 imgs/s/GPU, derived from the README's ~53 h / 200 epochs).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMGS_PER_SEC_PER_CHIP = 168.0  # 8xV100 MoCo-v2, BASELINE.md


def main():
    from moco_tpu.config import get_preset
    from moco_tpu.data.augment import build_two_crops_sharded, v2_aug_config
    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step

    devices = jax.devices()
    n_chips = len(devices)
    on_tpu = devices[0].platform == "tpu"
    mesh = create_mesh(n_chips)

    # per-chip batch 128 (vs the reference's 32/GPU) — TPU MXU wants batch
    if on_tpu:
        config = get_preset("imagenet-moco-v2").replace(
            batch_size=128 * n_chips, dataset="synthetic"
        )
        steps, warmup = 20, 10
    else:  # CPU fallback so the bench is runnable anywhere (tiny proxy)
        config = get_preset("imagenet-moco-v2").replace(
            arch="resnet_tiny", cifar_stem=True, compute_dtype="float32",
            image_size=32, batch_size=8 * n_chips, num_negatives=64 * n_chips,
            embed_dim=32, dataset="synthetic",
        )
        steps, warmup = 5, 2

    model = build_encoder(config)
    tx, sched = build_optimizer(config, steps_per_epoch=1000)
    state = create_train_state(
        jax.random.key(0),
        model,
        tx,
        (config.batch_size // n_chips, config.image_size, config.image_size, 3),
        config.num_negatives,
        config.embed_dim,
    )
    step_fn = build_train_step(config, model, tx, mesh, 1000, sched)

    aug_cfg = v2_aug_config(config.image_size)
    two_crops = build_two_crops_sharded(aug_cfg, mesh)
    # one staged uint8 batch; re-augmented on device every step (two_crops),
    # representing the steady-state input path with host decode amortized
    stage = config.image_size + config.image_size // 8
    rng = np.random.RandomState(0)
    imgs_u8 = jnp.asarray(
        rng.randint(0, 256, (config.batch_size, stage, stage, 3), dtype=np.uint8)
    )
    data_key = jax.random.key(1)

    def one_step(state, i):
        im_q, im_k = two_crops(imgs_u8, jax.random.fold_in(data_key, i))
        return step_fn(state, im_q, im_k)

    # Timing notes (measured on the sandbox's tunneled v5e):
    # - `block_until_ready` does NOT reliably synchronize on the experimental
    #   axon PJRT relay — only a real device→host transfer does, so we sync
    #   with float(loss).
    # - the first executions after compile are relay-warmup (~seconds);
    #   steady state needs a generous warmup, then chained steps with one
    #   final sync amortize the ~70 ms relay round-trip.
    for i in range(warmup):
        state, metrics = one_step(state, i)
    float(metrics["loss"])

    best = float("inf")
    for r in range(2):  # best-of-2 rounds to dodge relay noise
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = one_step(state, (r + 1) * 1000 + i)
        float(metrics["loss"])
        best = min(best, (time.perf_counter() - t0) / steps)

    imgs_per_sec = config.batch_size / best
    per_chip = imgs_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "moco_v2_r50_pretrain_throughput_per_chip"
                if on_tpu
                else "moco_v2_tiny_cpu_proxy_throughput_per_chip",
                "value": round(per_chip, 2),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
