"""Learning-dynamics-at-horizon run (VERDICT r1 #4): config-1-shaped MoCo-v1
pretrain on the real chip for a few thousand steps with the kNN monitor.
Writes the per-epoch curve to runs/horizon_r2.log; the committed log is the
evidence behind test_smoke_train's hardened thresholds."""
import json, os, sys, time
import jax
from moco_tpu.config import get_preset
from moco_tpu.train import train

cfg = get_preset("cifar10-moco-v1").replace(
    arch="resnet18", cifar_stem=True, dataset="synthetic", image_size=32,
    batch_size=256, num_negatives=4096, embed_dim=128, lr=0.06, cos=True,
    epochs=25, steps_per_epoch=128,           # 3200 steps over a 2048-sample set
    knn_monitor=True, knn_bank_size=2048, num_classes=10,
    ckpt_dir="", tb_dir="", print_freq=64, num_workers=1,
    compute_dtype="bfloat16" if jax.default_backend() == "tpu" else "float32",
)
t0 = time.time()
state, metrics = train(cfg)
os.makedirs("runs", exist_ok=True)
print(json.dumps({"final_knn_top1": metrics.get("knn_top1"),
                  "final_loss": metrics.get("loss"),
                  "steps": int(state.step), "wall_s": round(time.time()-t0,1),
                  "backend": jax.default_backend()}))
